"""Write-once-register actor interface (ref: src/actor/write_once_register.rs).

Same harness shape as `stateright_tpu.actor.register` plus a `PutFail`
response (a later write of a different value fails), recorded as `WriteFail`
against a `WORegister` spec.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..semantics.register import ReadOk, WriteFail, WriteOk
from . import Id, Out
from .register import (
    ClientState,
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
)

__all__ = [
    "Internal",
    "Put",
    "Get",
    "PutOk",
    "PutFail",
    "GetOk",
    "WORegisterClient",
    "RegisterServer",
    "record_invocations",
    "record_returns",
]


@dataclass(frozen=True)
class PutFail:
    request_id: int

    def __repr__(self):
        return f"PutFail({self.request_id})"


# Identical to the read/write register's recorder because this port shares the
# Put/Get message classes across both protocols
# (ref: src/actor/write_once_register.rs:39-64).
from .register import record_invocations  # noqa: F401,E402


def record_returns(cfg, history, env):
    """Pass to `ActorModel.record_msg_in`
    (ref: src/actor/write_once_register.rs:67-97)."""
    if isinstance(env.msg, GetOk):
        return history.on_return(env.dst, ReadOk(env.msg.value))
    if isinstance(env.msg, PutOk):
        return history.on_return(env.dst, WriteOk())
    if isinstance(env.msg, PutFail):
        return history.on_return(env.dst, WriteFail())
    return None


class WORegisterClient(RegisterClient):
    """Like `RegisterClient` but continues its script on `PutFail` too
    (ref: src/actor/write_once_register.rs:247-266)."""

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if (
            isinstance(msg, PutFail)
            and isinstance(state, ClientState)
            and state.awaiting == msg.request_id
        ):
            # Same continuation as PutOk.
            return super().on_msg(id, state, src, PutOk(msg.request_id), out)
        return super().on_msg(id, state, src, msg, out)
