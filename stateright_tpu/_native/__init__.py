"""Native (C++) runtime components, loaded via ctypes.

The reference implements its whole runtime natively (Rust); here the compute
path is JAX/XLA on device, and the host-side hot spots that remain CPU-bound
get C++ implementations compiled on first use with the toolchain baked into
the image (no pybind11 — plain C ABI + ctypes). Everything has a pure-Python
fallback, so a missing compiler degrades performance, never correctness.

Shared objects are cached next to the sources in `build/` keyed by source
mtime, so repeat imports don't pay the compile.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_lock = threading.Lock()
_cache: dict = {}


def _compile(name: str) -> str:
    src = os.path.join(_DIR, f"{name}.cpp")
    out = os.path.join(_BUILD, f"{name}.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(_BUILD, exist_ok=True)
    tmp = out + ".tmp"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src],
        check=True,
        capture_output=True,
    )
    os.replace(tmp, out)
    return out


def load(name: str):
    """ctypes.CDLL for `<name>.cpp`, compiled on demand; None when the
    toolchain is unavailable (callers fall back to Python)."""
    with _lock:
        if name in _cache:
            return _cache[name]
        try:
            lib = ctypes.CDLL(_compile(name))
        except (OSError, subprocess.CalledProcessError, FileNotFoundError):
            lib = None
        _cache[name] = lib
        return lib
