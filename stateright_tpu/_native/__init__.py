"""Native (C++) runtime components, loaded via ctypes.

The reference implements its whole runtime natively (Rust); here the compute
path is JAX/XLA on device, and the host-side hot spots that remain CPU-bound
get C++ implementations compiled on first use with the toolchain baked into
the image (no pybind11 — plain C ABI + ctypes). Everything has a pure-Python
fallback, so a missing compiler degrades performance, never correctness.

Shared objects are cached next to the sources in `build/` keyed by source
mtime, so repeat imports don't pay the compile.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_lock = threading.Lock()
_cache: dict = {}


def build(name: str, *, exe: bool = False, timeout: float = 300.0) -> str:
    """Compile `<name>.cpp` into the build cache (keyed by source mtime) and
    return the artifact path. `exe=False` builds a shared object for ctypes;
    `exe=True` builds a standalone optimized executable (used by the bench
    harness for the CPU baseline checker)."""
    src = os.path.join(_DIR, f"{name}.cpp")
    out = os.path.join(_BUILD, name + ("" if exe else ".so"))
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(_BUILD, exist_ok=True)
    flags = (
        ["-O3", "-march=native", "-pthread"]
        if exe
        else ["-O2", "-shared", "-fPIC"]
    )
    # Per-process temp name so concurrent compiles can't interleave output;
    # os.replace makes the publish atomic.
    tmp = f"{out}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-std=c++17", *flags, "-o", tmp, src],
            check=True,
            capture_output=True,
            timeout=timeout,
        )
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out


def _compile(name: str) -> str:
    return build(name)


def load(name: str):
    """ctypes.CDLL for `<name>.cpp`, compiled on demand; None when the
    toolchain is unavailable (callers fall back to Python)."""
    with _lock:
        if name in _cache:
            return _cache[name]
        try:
            lib = ctypes.CDLL(_compile(name))
        except (OSError, subprocess.CalledProcessError, FileNotFoundError):
            lib = None
        _cache[name] = lib
        return lib
