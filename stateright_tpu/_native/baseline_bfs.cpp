// Compiled CPU baseline: a multithreaded breadth-first model checker over the
// same workloads bench.py runs on device (Paxos-C and 2PC-N).
//
// Purpose (BASELINE.md): the reference's own baseline is its multithreaded
// Rust BfsChecker (ref: src/checker/bfs.rs:40-174) run via bench.sh, but this
// image ships no cargo/rustc toolchain, so the baseline is *approximated* with
// this C++ port — same search (frontier BFS, shared fingerprint-dedup visited
// set, per-state property evaluation, thread parallelism), same state spaces
// (validated against the reference's golden counts: 2pc-3=288, 2pc-5=8,832,
// paxos-2=16,668). It is a conservative stand-in: states are packed u32 lanes
// (cheaper per state than the reference's boxed BTreeMap/HashMap states), so
// beating this checker implies beating the reference's throughput a fortiori.
//
// Usage: baseline_bfs (paxos CLIENTS | 2pc RMS) [threads]
// Output (one line, reference report style, ref: src/report.rs:65-82):
//   model=<m> states=<generated> unique=<u> depth=<d> sec=<s> threads=<t>
//
// Model semantics are scalar ports of the validated tensor encodings
// (stateright_tpu/tensor/paxos.py, tensor/models.py), which themselves
// reproduce the reference actor model (examples/paxos.rs:106-254,
// examples/2pc.rs:59-147) at golden-count parity.

#include <algorithm>
#include <atomic>
#include <array>
#include <cstdint>
#include <functional>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <mutex>
#include <thread>
#include <unordered_set>
#include <vector>

namespace {

using u32 = uint32_t;
using u64 = uint64_t;

constexpr u32 EMPTY = 0xFFFFFFFFu;

// splitmix64 finalizer — stable fingerprint over packed lanes (mirrors
// tensor/fingerprint.py; exact value equality with the device fingerprint is
// not required, only injectivity per model).
inline u64 mix64(u64 h) {
  h = (h ^ (h >> 30)) * 0xBF58476D1CE4E5B9ull;
  h = (h ^ (h >> 27)) * 0x94D049BB133111EBull;
  return h ^ (h >> 31);
}

inline u64 fingerprint(const u32* lanes, int n) {
  u64 h = 0x5851F42D4C957F2Dull;
  for (int i = 0; i < n; ++i)
    h = mix64(h ^ (u64(lanes[i]) + 0x9E3779B97F4A7C15ull * u64(i + 1)));
  return h ? h : 1;
}

// ---------------------------------------------------------------------------
// Generic multithreaded frontier BFS over a Model with fixed-width states.
// Visited set: sharded unordered_set of fingerprints (the reference's
// DashMap<Fingerprint, _>, ref: src/checker/bfs.rs:29-31; fingerprint
// collisions silently merge states there too).
// ---------------------------------------------------------------------------

constexpr int SHARDS = 64;

template <typename Model>
struct Bfs {
  using State = typename Model::State;
  const Model& model;
  int threads;

  std::array<std::unordered_set<u64>, SHARDS> visited;
  std::array<std::mutex, SHARDS> locks;

  std::atomic<u64> generated{0};
  std::atomic<u64> property_violations{0};
  u64 unique = 0;
  int depth = 0;

  explicit Bfs(const Model& m, int t) : model(m), threads(t) {}

  bool insert(u64 fp) {
    int s = fp & (SHARDS - 1);
    std::lock_guard<std::mutex> g(locks[s]);
    return visited[s].insert(fp).second;
  }

  void run() {
    std::vector<State> frontier = model.init_states();
    generated += frontier.size();
    // Dedup initial states.
    {
      std::vector<State> uniq;
      for (const auto& s : frontier)
        if (insert(fingerprint(s.lanes.data(), Model::LANES))) uniq.push_back(s);
      unique = uniq.size();
      frontier.swap(uniq);
    }
    depth = 1;
    while (!frontier.empty()) {
      std::vector<std::vector<State>> next_per_thread(threads);
      std::atomic<size_t> cursor{0};
      auto worker = [&](int t) {
        auto& out = next_per_thread[t];
        std::vector<State> succs;
        size_t i;
        u64 local_gen = 0, local_viol = 0;
        std::vector<State> local_new;
        while ((i = cursor.fetch_add(1)) < frontier.size()) {
          const State& s = frontier[i];
          if (!model.properties_hold(s)) local_viol++;
          succs.clear();
          model.expand(s, succs);
          local_gen += succs.size();
          for (auto& n : succs)
            if (insert(fingerprint(n.lanes.data(), Model::LANES)))
              out.push_back(n);
        }
        generated += local_gen;
        property_violations += local_viol;
      };
      std::vector<std::thread> pool;
      for (int t = 1; t < threads; ++t) pool.emplace_back(worker, t);
      worker(0);
      for (auto& t : pool) t.join();
      frontier.clear();
      for (auto& v : next_per_thread) {
        unique += v.size();
        frontier.insert(frontier.end(), v.begin(), v.end());
        v.clear();
      }
      if (!frontier.empty()) depth++;
    }
  }
};

// ---------------------------------------------------------------------------
// 2PC — scalar port of tensor/models.py TensorTwoPhaseSys (itself matching
// examples/2pc.rs:59-147). One u64-worth of packed fields in lanes[0..1]:
//   rm_state: 2 bits per RM (0 working, 1 prepared, 2 committed, 3 aborted)
//   tm_state: 2 bits (0 init, 1 committed, 2 aborted)
//   tm_prepared: bitmask;  msgs: commit | abort | prepared_i bitmask
// ---------------------------------------------------------------------------

struct TwoPhase {
  static constexpr int LANES = 4;
  struct State { std::array<u32, LANES> lanes; };
  int rms;

  explicit TwoPhase(int n) : rms(n) {
    if (n > 16) { std::fprintf(stderr, "2pc: rms > 16\n"); std::exit(2); }
  }

  // lane0: rm_state (2b each); lane1: tm_state(2b) | tm_prepared<<2
  // lane2: msgs: bit0 commit, bit1 abort, bit(2+i) prepared_i; lane3: 0
  std::vector<State> init_states() const {
    State s{};
    return {s};
  }

  static u32 rm(const State& s, int i) { return (s.lanes[0] >> (2 * i)) & 3u; }
  static void set_rm(State& s, int i, u32 v) {
    s.lanes[0] = (s.lanes[0] & ~(3u << (2 * i))) | (v << (2 * i));
  }

  void expand(const State& s, std::vector<State>& out) const {
    u32 tm = s.lanes[1] & 3u;
    u32 prep = s.lanes[1] >> 2;
    u32 msgs = s.lanes[2];
    bool all_prep = prep == ((1u << rms) - 1u);
    if (tm == 0 && all_prep) {  // tm_commit
      State n = s; n.lanes[1] = 1u | (prep << 2); n.lanes[2] = msgs | 1u;
      out.push_back(n);
    }
    if (tm == 0) {  // tm_abort
      State n = s; n.lanes[1] = 2u | (prep << 2); n.lanes[2] = msgs | 2u;
      out.push_back(n);
    }
    for (int i = 0; i < rms; ++i) {
      if (tm == 0 && (msgs >> (2 + i)) & 1u) {  // tm_rcv_prepared
        State n = s; n.lanes[1] = tm | ((prep | (1u << i)) << 2);
        out.push_back(n);
      }
      if (rm(s, i) == 0) {  // working: rm_prepare, rm_choose_abort
        State n = s; set_rm(n, i, 1); n.lanes[2] = msgs | (1u << (2 + i));
        out.push_back(n);
        State a = s; set_rm(a, i, 3);
        out.push_back(a);
      }
      if (msgs & 1u) { State n = s; set_rm(n, i, 2); out.push_back(n); }
      if (msgs & 2u) { State n = s; set_rm(n, i, 3); out.push_back(n); }
    }
  }

  bool properties_hold(const State& s) const {  // "consistent" (always)
    bool any_abort = false, any_commit = false;
    for (int i = 0; i < rms; ++i) {
      any_abort |= rm(s, i) == 3;
      any_commit |= rm(s, i) == 2;
    }
    return !(any_abort && any_commit);
  }
};

// ---------------------------------------------------------------------------
// Paxos — scalar port of tensor/paxos.py TensorPaxos (C clients, 3 servers,
// unordered non-duplicating network, linearizability-tested register;
// actor semantics ref: examples/paxos.rs:106-254). State layout identical to
// the tensor encoding: [srvA, srvB] x 3, client lane, sorted envelope pool.
// ---------------------------------------------------------------------------

constexpr int S = 3;
constexpr int MAXPOOL = 24;

struct Paxos {
  static constexpr int LANES = 2 * S + 1 + MAXPOOL;
  struct State { std::array<u32, LANES> lanes; };

  int C;
  int NB, NLA, bb, bla, bprep, maj;
  int off_prop, off_acc, off_dec, off_accs;

  // Envelope vocabulary (mirrors tensor/paxos.py _build_vocab).
  int PUT0, GET0, PUTOK0, GETOK0, PREPARE0, PREPARED0, ACCEPT0, ACCEPTED0,
      DECIDED0, V;
  std::vector<u32> TYP, DST, BAL, PROP, LA, SRC, VAL;

  // Linearizability combo tables (mirrors _build_lin_tables).
  struct Combo {
    std::array<u32, 3> phase_mask;          // allowed phases per client
    std::array<int, 3> ret;                 // expected Get value; -1 free
    std::array<std::array<u32, 3>, 3> maxf; // frontier cap [client][peer]
  };
  std::vector<Combo> combos;

  mutable std::atomic<u32> max_pool_used{0};

  explicit Paxos(int clients) : C(clients) {
    if (C > 3) { std::fprintf(stderr, "paxos: clients > 3\n"); std::exit(2); }
    NB = 1 + C * S;
    NLA = 1 + C * S * C;
    auto bits = [](int n) { int b = 0; while ((1 << b) < n) b++; return b ? b : 1; };
    bb = bits(NB); bla = bits(NLA); bprep = 1 + bla; maj = S / 2 + 1;
    off_prop = bb; off_acc = bb + 2; off_dec = off_acc + bla;
    off_accs = off_dec + 1;
    build_vocab();
    build_lin_tables();
  }

  void build_vocab() {
    int NBALLOT = C * S;
    PUT0 = 0;
    GET0 = PUT0 + C;
    PUTOK0 = GET0 + C;
    GETOK0 = PUTOK0 + S * C;
    PREPARE0 = GETOK0 + C * C;
    PREPARED0 = PREPARE0 + NBALLOT * (S - 1);
    ACCEPT0 = PREPARED0 + NBALLOT * (S - 1) * NLA;
    ACCEPTED0 = ACCEPT0 + NBALLOT * C * (S - 1);
    DECIDED0 = ACCEPTED0 + NBALLOT * (S - 1);
    V = DECIDED0 + NBALLOT * C * (S - 1);
    TYP.assign(V, 0); DST.assign(V, 0); BAL.assign(V, 0); PROP.assign(V, 0);
    LA.assign(V, 0); SRC.assign(V, 0); VAL.assign(V, 0);
    auto leader = [&](int b) { return (b - 1) % S; };
    auto peer = [&](int l, int d) { return d + (d >= l ? 1 : 0); };
    for (int k = 0; k < C; ++k) {
      int i = PUT0 + k;
      TYP[i] = 0; DST[i] = (S + k) % S; PROP[i] = k; SRC[i] = S + k;
      i = GET0 + k;
      TYP[i] = 1; DST[i] = (S + k + 1) % S; PROP[i] = k; SRC[i] = S + k;
    }
    for (int s = 0; s < S; ++s)
      for (int k = 0; k < C; ++k) {
        int i = PUTOK0 + s * C + k;
        TYP[i] = 2; DST[i] = k; PROP[i] = k; SRC[i] = s;
      }
    for (int k = 0; k < C; ++k)
      for (int v = 0; v < C; ++v) {
        int i = GETOK0 + k * C + v;
        TYP[i] = 3; DST[i] = k; PROP[i] = k; VAL[i] = v;
        SRC[i] = (S + k + 1) % S;
      }
    for (int b = 1; b <= NBALLOT; ++b)
      for (int d = 0; d < S - 1; ++d) {
        int i = PREPARE0 + (b - 1) * (S - 1) + d;
        TYP[i] = 4; DST[i] = peer(leader(b), d); BAL[i] = b; SRC[i] = leader(b);
        for (int la = 0; la < NLA; ++la) {
          int j = PREPARED0 + ((b - 1) * (S - 1) + d) * NLA + la;
          TYP[j] = 5; DST[j] = leader(b); BAL[j] = b; LA[j] = la;
          SRC[j] = peer(leader(b), d);
        }
        i = ACCEPTED0 + (b - 1) * (S - 1) + d;
        TYP[i] = 7; DST[i] = leader(b); BAL[i] = b; SRC[i] = peer(leader(b), d);
        for (int k = 0; k < C; ++k) {
          i = ACCEPT0 + ((b - 1) * C + k) * (S - 1) + d;
          TYP[i] = 6; DST[i] = peer(leader(b), d); BAL[i] = b; PROP[i] = k;
          SRC[i] = leader(b);
          i = DECIDED0 + ((b - 1) * C + k) * (S - 1) + d;
          TYP[i] = 8; DST[i] = peer(leader(b), d); BAL[i] = b; PROP[i] = k;
          SRC[i] = leader(b);
        }
      }
  }

  void build_lin_tables() {
    // Enumerate per-client op-inclusion patterns (0: put in flight; 1: put
    // done, get not completed; 2: get included) x all order interleavings,
    // replay the register, and compile to constraint rows
    // (ref: src/semantics/linearizability.rs:193-280 — here the search is
    // precompiled because the workload's history shape is static).
    constexpr int NULLV = -2;
    std::vector<std::array<int, 3>> prefixes;
    std::array<int, 3> cur{};
    std::function<void(int)> gen = [&](int c) {
      if (c == C) { prefixes.push_back(cur); return; }
      for (int p = 0; p < 3; ++p) { cur[c] = p; gen(c + 1); }
    };
    gen(0);
    std::vector<Combo> all;
    for (auto& pre : prefixes) {
      std::vector<std::pair<int, char>> ops;
      for (int c = 0; c < C; ++c) {
        if (pre[c] >= 1) ops.emplace_back(c, 'p');
        if (pre[c] == 2) ops.emplace_back(c, 'g');
      }
      std::vector<std::vector<std::pair<int, char>>> seqs{{}};
      for (size_t n = 0; n < ops.size(); ++n) {
        std::vector<std::vector<std::pair<int, char>>> nxt;
        for (auto& seq : seqs) {
          auto used = [&](std::pair<int, char> op) {
            for (auto& o : seq) if (o == op) return true;
            return false;
          };
          for (auto& op : ops) {
            if (used(op)) continue;
            if (op.second == 'g' && !used({op.first, 'p'})) continue;
            auto s2 = seq; s2.push_back(op); nxt.push_back(s2);
          }
        }
        seqs.swap(nxt);
      }
      if (seqs.empty()) seqs = {{}};
      for (auto& seq : seqs) {
        Combo cb{};
        for (int c = 0; c < C; ++c) {
          if (pre[c] == 0) cb.phase_mask[c] = 1u << 0;
          else if (pre[c] == 1) cb.phase_mask[c] = (1u << 0) | (1u << 1);
          else cb.phase_mask[c] = (1u << 1) | (1u << 2);
        }
        int val = NULLV;
        std::array<int, 3> expected{NULLV, NULLV, NULLV};
        for (auto& [c, kind] : seq) {
          if (kind == 'p') val = c; else expected[c] = val;
        }
        for (int c = 0; c < C; ++c) {
          if (pre[c] == 2) cb.ret[c] = expected[c] == NULLV ? -1 : expected[c];
          else cb.ret[c] = -1;
        }
        for (int c = 0; c < C; ++c)
          for (int p = 0; p < C; ++p) cb.maxf[c][p] = 2;
        for (int c = 0; c < C; ++c) {
          if (pre[c] != 2) continue;
          size_t gpos = 0;
          for (size_t i = 0; i < seq.size(); ++i)
            if (seq[i] == std::make_pair(c, 'g')) { gpos = i; break; }
          for (int c2 = 0; c2 < C; ++c2) {
            if (c2 == c) continue;
            bool putb = false, getb = false;
            for (size_t i = 0; i < gpos; ++i) {
              if (seq[i] == std::make_pair(c2, 'p')) putb = true;
              if (seq[i] == std::make_pair(c2, 'g')) getb = true;
            }
            if (!putb) cb.maxf[c][c2] = 0;
            else if (!getb) cb.maxf[c][c2] = 1;
          }
        }
        all.push_back(cb);
      }
    }
    // Dedup identical constraint rows.
    for (auto& cb : all) {
      bool dup = false;
      for (auto& e : combos)
        if (std::memcmp(&e, &cb, sizeof(Combo)) == 0) { dup = true; break; }
      if (!dup) combos.push_back(cb);
    }
  }

  // -- field packing ---------------------------------------------------------

  struct Srv { u32 ballot, prop, accepted, decided, accepts; };
  Srv unpack(u32 a) const {
    return {a & ((1u << bb) - 1), (a >> off_prop) & 3u,
            (a >> off_acc) & ((1u << bla) - 1), (a >> off_dec) & 1u,
            (a >> off_accs) & ((1u << S) - 1)};
  }
  u32 pack(const Srv& s) const {
    return s.ballot | (s.prop << off_prop) | (s.accepted << off_acc) |
           (s.decided << off_dec) | (s.accepts << off_accs);
  }
  u32 r_of(u32 b) const { return b == 0 ? 0 : (b - 1) / S + 1; }

  std::vector<State> init_states() const {
    State s{};
    for (int i = 0; i < MAXPOOL; ++i) s.lanes[2 * S + 1 + i] = EMPTY;
    for (int k = 0; k < C; ++k) s.lanes[2 * S + 1 + k] = u32(PUT0 + k);
    return {s};
  }

  void expand(const State& st, std::vector<State>& out) const {
    const u32* pool = &st.lanes[2 * S + 1];
    u32 clients = st.lanes[2 * S];
    for (int slot = 0; slot < MAXPOOL; ++slot) {
      u32 e = pool[slot];
      if (e == EMPTY) break;                      // sorted: EMPTY at the end
      if (slot > 0 && pool[slot - 1] == e) continue;  // one Deliver per distinct
      u32 typ = TYP[e], dst = DST[e], bal = BAL[e], prp = PROP[e],
          lam = LA[e], src = SRC[e], val = VAL[e];
      bool is_server = typ == 0 || typ == 1 || typ >= 4;
      Srv sv = unpack(is_server ? st.lanes[2 * dst] : 0);
      u32 sB = is_server ? st.lanes[2 * dst + 1] : 0;
      u32 cfield = is_server ? 0 : (clients >> (8 * dst)) & 0xFFu;
      u32 cphase = cfield & 3u;
      bool not_dec = sv.decided == 0;

      Srv nv = sv; u32 nB = sB; u32 ncf = cfield;
      u32 em[3] = {EMPTY, EMPTY, EMPTY};
      bool ok = false;

      switch (typ) {
        case 0:  // Put (ref: examples/paxos.rs:163-183)
          if (not_dec && sv.prop == 0) {
            u32 nb = 1 + r_of(sv.ballot) * S + dst;
            nv = {nb, prp + 1, sv.accepted, 0, 0};
            nB = (1u | (sv.accepted << 1)) << (dst * bprep);
            em[0] = u32(PREPARE0 + (nb - 1) * (S - 1));
            em[1] = em[0] + 1;
            ok = true;
          }
          break;
        case 1:  // Get — reply only when decided (ref: paxos.rs:145-157)
          if (!not_dec) {
            u32 vprop = sv.accepted > 0 ? (sv.accepted - 1) % C : 0;
            em[0] = u32(GETOK0 + prp * C + vprop);
            ok = true;
          }
          break;
        case 4:  // Prepare (ref: paxos.rs:186-192)
          if (not_dec && sv.ballot < bal) {
            nv = {bal, sv.prop, sv.accepted, 0, sv.accepts};
            u32 lead = (bal - 1) % S;
            u32 slot2 = dst - (dst > lead ? 1 : 0);
            em[0] = u32(PREPARED0 + ((bal - 1) * (S - 1) + slot2) * NLA +
                        sv.accepted);
            ok = true;
          }
          break;
        case 5: {  // Prepared (ref: paxos.rs:193-231)
          if (not_dec && bal == sv.ballot) {
            u32 pbit = 1u << (src * bprep);
            bool already = (sB & pbit) != 0;
            u32 addB = sB | pbit | (lam << (src * bprep + 1));
            u32 pres = 0, best_la = 0;
            for (int j = 0; j < S; ++j) {
              u32 pj = (addB >> (j * bprep)) & 1u;
              u32 laj = (addB >> (j * bprep + 1)) & ((1u << bla) - 1);
              pres += pj;
              if (pj && laj > best_la) best_la = laj;
            }
            bool quorum = !already && pres == u32(maj);
            u32 chosen = best_la > 0 ? (best_la - 1) % C : sv.prop - 1;
            if (quorum) {
              em[0] = u32(ACCEPT0 + ((bal - 1) * C + chosen) * (S - 1));
              em[1] = em[0] + 1;
              nv = {sv.ballot, chosen + 1, 1 + (bal - 1) * u32(C) + chosen, 0,
                    1u << dst};
            } else {
              nv = {sv.ballot, sv.prop, sv.accepted, 0, sv.accepts};
            }
            nB = addB;
            ok = true;
          }
          break;
        }
        case 6:  // Accept (ref: paxos.rs:232-240)
          if (not_dec && sv.ballot <= bal) {
            nv = {bal, sv.prop, 1 + (bal - 1) * u32(C) + prp, 0, sv.accepts};
            u32 lead = (bal - 1) % S;
            u32 slot2 = dst - (dst > lead ? 1 : 0);
            em[0] = u32(ACCEPTED0 + (bal - 1) * (S - 1) + slot2);
            ok = true;
          }
          break;
        case 7: {  // Accepted (ref: paxos.rs:241-263)
          if (not_dec && bal == sv.ballot) {
            u32 abit = 1u << src;
            u32 naccs = sv.accepts | abit;
            u32 cnt = 0;
            for (int j = 0; j < S; ++j) cnt += (naccs >> j) & 1u;
            bool aq = !(sv.accepts & abit) && cnt == u32(maj);
            if (aq) {
              em[0] = u32(DECIDED0 + ((bal - 1) * C + (sv.prop - 1)) * (S - 1));
              em[1] = em[0] + 1;
              em[2] = u32(PUTOK0 + dst * C + (sv.prop - 1));
            }
            nv = {sv.ballot, sv.prop, sv.accepted, aq ? 1u : 0u, naccs};
            ok = true;
          }
          break;
        }
        case 8:  // Decided (ref: paxos.rs:264-271)
          if (not_dec) {
            nv = {bal, sv.prop, 1 + (bal - 1) * u32(C) + prp, 1, sv.accepts};
            ok = true;
          }
          break;
        case 2:  // PutOk -> client issues Get, captures real-time frontier
          if (cphase == 0) {
            u32 frontier = 0, fshift = 0;
            for (int c2 = 0; c2 < C; ++c2) {
              if (u32(c2) == dst) continue;
              u32 f2 = (clients >> (8 * c2)) & 3u;
              u32 comp = f2 == 2 ? 2 : (f2 == 1 ? 1 : 0);
              frontier |= comp << fshift;
              fshift += 2;
            }
            ncf = 1u | (frontier << 4);
            em[0] = u32(GET0 + dst);
            ok = true;
          }
          break;
        case 3:  // GetOk -> client done
          if (cphase == 1) {
            ncf = (cfield & ~3u & ~(3u << 2)) | 2u | (val << 2);
            ok = true;
          }
          break;
      }
      if (!ok) continue;

      State n = st;
      if (is_server) {
        n.lanes[2 * dst] = pack(nv);
        n.lanes[2 * dst + 1] = nB;
      } else {
        n.lanes[2 * S] = (clients & ~(0xFFu << (8 * dst))) | (ncf << (8 * dst));
      }
      // Pool: drop delivered instance, add emissions, re-sort.
      u32* np = &n.lanes[2 * S + 1];
      int cnt = 0;
      u32 tmp[MAXPOOL + 3];
      for (int i = 0; i < MAXPOOL; ++i)
        if (i != slot && pool[i] != EMPTY) tmp[cnt++] = pool[i];
      for (int i = 0; i < 3; ++i)
        if (em[i] != EMPTY) tmp[cnt++] = em[i];
      if (cnt > MAXPOOL) { std::fprintf(stderr, "pool overflow\n"); std::exit(3); }
      std::sort(tmp, tmp + cnt);
      u32 prev = max_pool_used.load(std::memory_order_relaxed);
      while (u32(cnt) > prev &&
             !max_pool_used.compare_exchange_weak(prev, u32(cnt))) {}
      for (int i = 0; i < cnt; ++i) np[i] = tmp[i];
      for (int i = cnt; i < MAXPOOL; ++i) np[i] = EMPTY;
      out.push_back(n);
    }
  }

  bool properties_hold(const State& st) const {  // "linearizable" (always)
    u32 clients = st.lanes[2 * S];
    std::array<u32, 3> phase{}, ret{};
    std::array<std::array<u32, 3>, 3> frontier{};
    for (int c = 0; c < C; ++c) {
      phase[c] = (clients >> (8 * c)) & 3u;
      ret[c] = (clients >> (8 * c + 2)) & 3u;
      for (int c2 = 0; c2 < C; ++c2) {
        if (c2 == c) { frontier[c][c2] = 0; continue; }
        int pslot = c2 - (c2 > c ? 1 : 0);
        frontier[c][c2] = (clients >> (8 * c + 4 + 2 * pslot)) & 3u;
      }
    }
    for (auto& cb : combos) {
      bool okc = true;
      for (int c = 0; c < C && okc; ++c) {
        if (!((cb.phase_mask[c] >> phase[c]) & 1u)) { okc = false; break; }
        bool has_get = (cb.phase_mask[c] & (1u << 2)) != 0;
        if (has_get && phase[c] != 1 &&
            !(cb.ret[c] >= 0 && ret[c] == u32(cb.ret[c]))) {
          okc = false;
          break;
        }
        for (int c2 = 0; c2 < C; ++c2)
          if (frontier[c][c2] > cb.maxf[c][c2]) { okc = false; break; }
      }
      if (okc) return true;
    }
    return false;
  }
};

// ---------------------------------------------------------------------------
// increment_lock — scalar port of tensor/models.py TensorIncrementLock
// (itself matching examples/increment_lock.rs). Lanes: [i, lock, t0, pc0, ...]
// ---------------------------------------------------------------------------

struct IncrementLock {
  static constexpr int LANES = 16;  // supports up to 7 threads
  struct State { std::array<u32, LANES> lanes; };
  int threads_n;

  explicit IncrementLock(int n) : threads_n(n) {
    if (n > 7) { std::fprintf(stderr, "increment_lock: n > 7\n"); std::exit(2); }
  }

  std::vector<State> init_states() const {
    State s{};
    return {s};
  }

  void expand(const State& s, std::vector<State>& out) const {
    u32 i = s.lanes[0], lock = s.lanes[1];
    for (int t = 0; t < threads_n; ++t) {
      u32 tv = s.lanes[2 + 2 * t], pc = s.lanes[3 + 2 * t];
      if (pc == 0 && !lock) {        // lock
        State n = s; n.lanes[1] = 1; n.lanes[3 + 2 * t] = 1; out.push_back(n);
      } else if (pc == 1) {          // read
        State n = s; n.lanes[2 + 2 * t] = i; n.lanes[3 + 2 * t] = 2;
        out.push_back(n);
      } else if (pc == 2) {          // write
        State n = s; n.lanes[0] = tv + 1; n.lanes[3 + 2 * t] = 3;
        out.push_back(n);
      } else if (pc == 3 && lock) {  // release
        State n = s; n.lanes[1] = 0; n.lanes[3 + 2 * t] = 4; out.push_back(n);
      }
    }
  }

  bool properties_hold(const State& s) const {  // fin && mutex (always)
    u32 done = 0, held = 0;
    for (int t = 0; t < threads_n; ++t) {
      u32 pc = s.lanes[3 + 2 * t];
      done += pc >= 3;
      held += pc >= 1 && pc < 4;
    }
    return done == s.lanes[0] && held <= 1;
  }
};

}  // namespace

template <typename Model>
static void run(const Model& model, int threads, const char* name) {
  Bfs<Model> bfs(model, threads);
  auto t0 = std::chrono::steady_clock::now();
  bfs.run();
  double sec = std::chrono::duration<double>(
                   std::chrono::steady_clock::now() - t0).count();
  std::printf(
      "model=%s states=%llu unique=%llu depth=%d sec=%.6f threads=%d "
      "violations=%llu\n",
      name, (unsigned long long)bfs.generated.load(),
      (unsigned long long)bfs.unique, bfs.depth, sec, threads,
      (unsigned long long)bfs.property_violations.load());
}

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s (paxos CLIENTS | 2pc RMS) [threads]\n",
                 argv[0]);
    return 2;
  }
  int n = std::atoi(argv[2]);
  int threads = argc > 3 ? std::atoi(argv[3])
                         : int(std::thread::hardware_concurrency());
  if (threads < 1) threads = 1;
  if (std::strcmp(argv[1], "paxos") == 0) {
    Paxos m(n);
    run(m, threads, "paxos");
    std::fprintf(stderr, "max_pool_used=%u\n", m.max_pool_used.load());
  } else if (std::strcmp(argv[1], "2pc") == 0) {
    TwoPhase m(n);
    run(m, threads, "2pc");
  } else if (std::strcmp(argv[1], "increment_lock") == 0) {
    IncrementLock m(n);
    run(m, threads, "increment_lock");
  } else {
    std::fprintf(stderr, "unknown model %s\n", argv[1]);
    return 2;
  }
  return 0;
}
