// Native consistency-semantics serializer.
//
// Implements the exhaustive backtracking interleaving search of the
// linearizability / sequential-consistency testers (the reference's recursive
// `serialize`, src/semantics/linearizability.rs:193-280 and
// src/semantics/sequential_consistency.rs) over the three built-in reference
// objects (Register, write-once Register, Vec/stack). The search is the
// host-side hot spot of semantics-checked models (SURVEY.md §7 calls the
// linearizability property cost "the throughput killer"), so it is the part of
// the runtime that earns a native implementation; arbitrary user-defined
// SequentialSpecs still take the Python path.
//
// The search must visit candidate interleavings in exactly the order the
// Python implementation does (thread index ascending, completed-op branch
// preferred only in the sense that each thread offers exactly one branch per
// step), so the serialization it returns is identical — tests compare the two
// element-for-element.
//
// Value model: Python interns every op/ret payload to a dense int64 before the
// call; `LenOk` carries its raw length. The C ABI is plain arrays so the
// binding layer stays ctypes-only (no pybind11 in this image).

#include <cstdint>
#include <vector>

namespace {

enum SpecKind : int32_t {
  SPEC_REGISTER = 0,
  SPEC_WO_REGISTER = 1,
  SPEC_VEC = 2,
};

// Register / WORegister ops & rets.
enum RegOp : int32_t { OP_WRITE = 0, OP_READ = 1 };
enum RegRet : int32_t { RET_WRITE_OK = 0, RET_WRITE_FAIL = 1, RET_READ_OK = 2 };
// Vec ops & rets.
enum VecOp : int32_t { OP_PUSH = 0, OP_POP = 1, OP_LEN = 2 };
enum VecRet : int32_t { RET_PUSH_OK = 0, RET_POP_OK = 1, RET_LEN_OK = 2 };

struct Spec {
  int32_t kind;
  int64_t none_id;
  // Register / WORegister state.
  int64_t value;
  bool written;  // WORegister only
  // Vec state.
  std::vector<int64_t> stack;

  // Apply a completed (op, ret) step if the spec can produce `ret` for `op`
  // (SequentialSpec::is_valid_step). Returns false (state unchanged) if not.
  bool valid_step(int32_t op_kind, int64_t op_val, int32_t ret_kind,
                  int64_t ret_val) {
    switch (kind) {
      case SPEC_REGISTER:
        if (op_kind == OP_WRITE) {
          if (ret_kind != RET_WRITE_OK) return false;
          value = op_val;
          return true;
        }
        return ret_kind == RET_READ_OK && ret_val == value;
      case SPEC_WO_REGISTER:
        if (op_kind == OP_WRITE) {
          if (ret_kind == RET_WRITE_OK) {
            if (!written) {
              value = op_val;
              written = true;
              return true;
            }
            return op_val == value;
          }
          if (ret_kind == RET_WRITE_FAIL)
            return written && op_val != value;
          return false;
        }
        return ret_kind == RET_READ_OK &&
               ret_val == (written ? value : none_id);
      case SPEC_VEC:
        // VecSpec uses the default is_valid_step: invoke, compare rets.
        if (op_kind == OP_PUSH) {
          if (ret_kind != RET_PUSH_OK) return false;
          stack.push_back(op_val);
          return true;
        }
        if (op_kind == OP_POP) {
          if (ret_kind != RET_POP_OK) return false;
          if (stack.empty()) return ret_val == none_id;
          if (ret_val != stack.back()) return false;
          stack.pop_back();
          return true;
        }
        // OP_LEN: LenOk carries the raw length.
        return ret_kind == RET_LEN_OK &&
               ret_val == static_cast<int64_t>(stack.size());
    }
    return false;
  }

  // Apply an in-flight op unconditionally (SequentialSpec::invoke); the ret is
  // whatever the spec produces, so any op applies.
  void invoke(int32_t op_kind, int64_t op_val) {
    switch (kind) {
      case SPEC_REGISTER:
        if (op_kind == OP_WRITE) value = op_val;
        return;
      case SPEC_WO_REGISTER:
        if (op_kind == OP_WRITE && !written) {
          value = op_val;
          written = true;
        }
        return;
      case SPEC_VEC:
        if (op_kind == OP_PUSH) stack.push_back(op_val);
        else if (op_kind == OP_POP && !stack.empty()) stack.pop_back();
        return;
    }
  }
};

struct Search {
  int32_t T;
  bool linearizable;
  // Completed history, flattened per thread.
  const int64_t* hist_offset;  // [T+1] into the N-length arrays
  const int32_t* op_kind;
  const int64_t* op_val;
  const int32_t* ret_kind;
  const int64_t* ret_val;
  // Real-time prerequisites per completed op (linearizability only).
  const int64_t* prereq_offset;  // [N+1]
  const int64_t* prereq_peer;
  const int64_t* prereq_time;
  // In-flight op per thread (optional).
  const uint8_t* ifl_present;
  const int32_t* ifl_op_kind;
  const int64_t* ifl_op_val;
  const int64_t* ifl_prereq_offset;  // [T+1]
  const int64_t* ifl_prereq_peer;
  const int64_t* ifl_prereq_time;

  // Mutable search state.
  std::vector<int64_t> pos;      // next completed index per thread (absolute)
  std::vector<uint8_t> ifl_done; // in-flight op consumed?
  Spec spec;
  // Output order: (thread, is_inflight) per consumed op.
  std::vector<int32_t> out_thread;
  std::vector<uint8_t> out_ifl;

  int64_t hist_len(int32_t t) const { return hist_offset[t + 1] - hist_offset[t]; }
  int64_t local_pos(int32_t t) const { return pos[t] - hist_offset[t]; }

  // Python's _violates_real_time: a prerequisite (peer, min_time) is violated
  // when the peer still has unconsumed completed ops and its next op's
  // original index is <= min_time.
  bool violates(const int64_t* peers, const int64_t* times, int64_t n) const {
    for (int64_t i = 0; i < n; ++i) {
      int32_t peer = static_cast<int32_t>(peers[i]);
      if (pos[peer] < hist_offset[peer + 1] && local_pos(peer) <= times[i])
        return true;
    }
    return false;
  }

  bool done() const {
    for (int32_t t = 0; t < T; ++t)
      if (pos[t] < hist_offset[t + 1]) return false;
    return true;
  }

  bool serialize() {
    if (done()) return true;  // in-flight ops need not take effect
    for (int32_t t = 0; t < T; ++t) {
      if (pos[t] >= hist_offset[t + 1]) {
        // Case 1: only a possibly-in-flight op remains for this thread.
        if (!ifl_present[t] || ifl_done[t]) continue;
        if (linearizable &&
            violates(ifl_prereq_peer + ifl_prereq_offset[t],
                     ifl_prereq_time + ifl_prereq_offset[t],
                     ifl_prereq_offset[t + 1] - ifl_prereq_offset[t]))
          continue;
        Spec saved = spec;
        spec.invoke(ifl_op_kind[t], ifl_op_val[t]);
        ifl_done[t] = 1;
        out_thread.push_back(t);
        out_ifl.push_back(1);
        if (serialize()) return true;
        out_thread.pop_back();
        out_ifl.pop_back();
        ifl_done[t] = 0;
        spec = saved;
      } else {
        // Case 2: consume the thread's next completed op.
        int64_t i = pos[t];
        pos[t] += 1;  // Python pops before the real-time check
        bool viol = linearizable &&
                    violates(prereq_peer + prereq_offset[i],
                             prereq_time + prereq_offset[i],
                             prereq_offset[i + 1] - prereq_offset[i]);
        if (!viol) {
          Spec saved = spec;
          if (spec.valid_step(op_kind[i], op_val[i], ret_kind[i], ret_val[i])) {
            out_thread.push_back(t);
            out_ifl.push_back(0);
            if (serialize()) return true;
            out_thread.pop_back();
            out_ifl.pop_back();
          }
          spec = saved;
        }
        pos[t] -= 1;
      }
    }
    return false;
  }
};

}  // namespace

extern "C" {

// Returns 1 if serializable (out arrays filled, *out_len set), 0 if not,
// -1 on bad arguments. Out arrays must have capacity N + T.
int32_t srt_serialize(
    int32_t spec_kind, int32_t linearizable, const int64_t* spec_state,
    int64_t spec_state_len, int64_t none_id, int32_t T,
    const int64_t* hist_offset, const int32_t* op_kind, const int64_t* op_val,
    const int32_t* ret_kind, const int64_t* ret_val,
    const int64_t* prereq_offset, const int64_t* prereq_peer,
    const int64_t* prereq_time, const uint8_t* ifl_present,
    const int32_t* ifl_op_kind, const int64_t* ifl_op_val,
    const int64_t* ifl_prereq_offset, const int64_t* ifl_prereq_peer,
    const int64_t* ifl_prereq_time, int32_t* out_thread_arr,
    uint8_t* out_ifl_arr, int64_t* out_len) {
  Search s;
  s.T = T;
  s.linearizable = linearizable != 0;
  s.hist_offset = hist_offset;
  s.op_kind = op_kind;
  s.op_val = op_val;
  s.ret_kind = ret_kind;
  s.ret_val = ret_val;
  s.prereq_offset = prereq_offset;
  s.prereq_peer = prereq_peer;
  s.prereq_time = prereq_time;
  s.ifl_present = ifl_present;
  s.ifl_op_kind = ifl_op_kind;
  s.ifl_op_val = ifl_op_val;
  s.ifl_prereq_offset = ifl_prereq_offset;
  s.ifl_prereq_peer = ifl_prereq_peer;
  s.ifl_prereq_time = ifl_prereq_time;

  s.spec.kind = spec_kind;
  s.spec.none_id = none_id;
  s.spec.written = false;
  s.spec.value = 0;
  switch (spec_kind) {
    case SPEC_REGISTER:
      if (spec_state_len != 1) return -1;
      s.spec.value = spec_state[0];
      break;
    case SPEC_WO_REGISTER:
      if (spec_state_len != 2) return -1;
      s.spec.value = spec_state[0];
      s.spec.written = spec_state[1] != 0;
      break;
    case SPEC_VEC:
      s.spec.stack.assign(spec_state, spec_state + spec_state_len);
      break;
    default:
      return -1;
  }

  s.pos.resize(T);
  s.ifl_done.assign(T, 0);
  for (int32_t t = 0; t < T; ++t) s.pos[t] = hist_offset[t];

  if (!s.serialize()) return 0;
  int64_t n = static_cast<int64_t>(s.out_thread.size());
  for (int64_t i = 0; i < n; ++i) {
    out_thread_arr[i] = s.out_thread[i];
    out_ifl_arr[i] = s.out_ifl[i];
  }
  *out_len = n;
  return 1;
}

}  // extern "C"
