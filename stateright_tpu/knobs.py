"""The ONE registry of engine-knob string literals.

Before this module every spine spelled its own copy of the knob universes:
`FrontierSearch.INSERT_VARIANTS` named four insert designs, ResidentSearch
re-listed them inside an error message, the check-service scheduler re-typed
the store kinds, and `tensor/costmodel.py` kept a parallel variant alphabet —
the exact triple-implementation drift ROADMAP item 3's step-core refactor
will remove.  Until that refactor lands, this module is the drift *bound*:
every validation site imports its universe from here, and the srlint pass
(`stateright_tpu/analysis/`) flags any knob literal that is compared against
a variable without being a member of the registry — a typo'd
`store == "teired"` fails lint, not a benchmark three rounds later.

Deliberately pure Python (no jax import): the cost model, the analysis CLI,
and host-only tooling all read it without touching a backend.
"""

from __future__ import annotations

#: Visited-set insert designs accepted by FrontierSearch/ResidentSearch/
#: ShardedSearch (`insert_variant=`). "pallas" is the partitioned-VMEM
#: route-then-probe kernel (tensor/pallas_hashtable.py — SURVEY §7's
#: prescribed "open-addressing table in HBM updated by a Pallas kernel");
#: on non-TPU backends it runs under Pallas interpret mode with exact
#: set/is_new parity to the XLA designs. The name → insert-fn dispatch
#: lives in ONE module, tensor/inserts.py, which all three engines import
#: (check_registry() pins the two against each other).
INSERT_VARIANTS = ("sort", "phased", "capped", "capped-phased", "pallas")

#: The subset of INSERT_VARIANTS built on the phased (claim-then-probe)
#: insert — these require the split table layout (hashtable's phased impl
#: has no kv lowering). Derived, not restated: srlint SR005 flags literal
#: copies of this subset exactly like full-universe restatements.
PHASED_VARIANTS = tuple(v for v in INSERT_VARIANTS if v.endswith("phased"))

#: Hash-table layouts (`table_layout=`): split lo/hi arrays vs interleaved
#: 64-slot kv buckets (hashtable._insert_impl_kv).
TABLE_LAYOUTS = ("split", "kv")

#: State-store kinds (`store=`): device-only hot set vs the two-tier
#: device + host-spill store (stateright_tpu/store/).
STORE_KINDS = ("device", "tiered")

#: Queue-append variants (`append=`): whole-array row scatter vs
#: compact-then-dynamic_update_slice (frontier.resolve_append).
APPEND_KINDS = ("scatter", "dus")

#: Engine spines (supervisor/adapter `engine=` selectors, chaos-plane
#: `engine=` context). "simulation" is the fourth checker mode
#: (tensor/simulation.py) — a first-class spine for faults/obs/bench
#: purposes, though the supervisor's degrade ladder drives the three
#: exhaustive spines only.
ENGINES = ("frontier", "resident", "sharded", "simulation")

#: Checker modes accepted by `CheckerBuilder.spawn_tpu(mode=)`: the batched
#: frontier search (the default) vs the device random-simulation engine
#: (tensor/simulation.py — the reference's fourth checker mode, SURVEY L2).
CHECKER_MODES = ("search", "simulation")

#: Device-simulation dedup designs (`dedup=` on DeviceSimulation /
#: spawn_simulation(device=True)): "trace" keeps an exact per-walk visited
#: table per lane (host SimulationChecker parity — no global dedup, so
#: unique_state_count == state_count), "shared" keeps a small per-walk depth
#: ring for cycle detection plus ONE global visited table shared by every
#: walk (the tensor/inserts.py dispatch table — capped/pallas variants,
#: job-salted fingerprints) so unique_state_count is real coverage and
#: stale walks can be restarted.
SIM_DEDUP_KINDS = ("trace", "shared")

#: Cost-model variant alphabet (tensor/costmodel.py) — the (table_layout,
#: insert_variant) product collapsed to the designs the roofline model
#: distinguishes. Kept here so the mapping below is checkable by lint/tests.
COST_VARIANTS = ("split", "kv", "phased", "capped", "capped-kv", "pallas")

#: Corpus warm-start match kinds (store/warm.py — the ONE warm-start seam,
#: ROADMAP item 4): "exact" replays a complete entry published under this
#: run's own content key, "near" replays a complete entry from the same
#: definition-hash family (different table packing; membership and results
#: are packing-invariant), "partial" resumes an incomplete entry's frontier
#: snapshot and continues the search naturally. "delta" is the Spec-CI
#: rung (store/specdelta.py): the DEFINITION changed, but the factored
#: component digests prove the edit salvageable — a properties-only edit
#: replays the visited set with verdicts re-evaluated, a boundary edit
#: replays or continues from a re-derived frontier; expand/init edits
#: refuse (counted, cold, never wrong). Every engine's warm path and the
#: `job.warm_start` event `kind` field draw from this tuple;
#: check_registry() pins the per-engine aliases against it.
WARM_KINDS = ("exact", "near", "partial", "delta")

#: Blob-store backends (`faults/blobstore.py`'s `backend_of` scheme
#: dispatch, the `--backend` smoke selector, the bench per-backend legs):
#: "file" is the local filesystem (plain path / ``file://``), "blob" the
#: in-house HTTP emulator (``blob://host:port``), "s3" and "gs" the
#: managed providers (``s3://bucket``/``gs://bucket`` — dialect
#: emulators in `faults/blobdialect.py` serve them hermetically). First
#: member is the non-wire default; `backend_of` dispatches on the rest,
#: in order, as URI schemes.
BLOB_BACKENDS = ("file", "blob", "s3", "gs")


def check_registry() -> list:
    """Cross-module drift probe used by `python -m stateright_tpu.analysis`:
    import every module that re-states a knob universe and report any
    disagreement with this registry (empty list = no drift). Imports are
    local so host-only callers (cost model, lint fixtures) never pay for
    jax."""
    problems: list[str] = []

    try:
        from .tensor import costmodel
    except ModuleNotFoundError as e:
        # The costmodel module is jax-free but lives under the jax-importing
        # tensor package; on a jax-free image the cross-module probe simply
        # cannot run (srlint SR005 still covers literal drift there).
        if e.name and e.name.split(".")[0] in ("jax", "jaxlib"):
            return problems
        raise

    # costmodel re-exports the registry tuple by reference; a set-equality
    # check would be vacuous, so probe that the alias is still an alias —
    # re-typing the tuple in costmodel.py is exactly the drift this guards.
    if costmodel.INSERT_VARIANTS is not COST_VARIANTS:
        problems.append(
            "costmodel.INSERT_VARIANTS is a restated copy, not the "
            "knobs.COST_VARIANTS alias: "
            f"{sorted(costmodel.INSERT_VARIANTS)} vs {sorted(COST_VARIANTS)}"
        )
    for (layout, variant), cost in costmodel.ENGINE_VARIANTS.items():
        if layout not in TABLE_LAYOUTS:
            problems.append(
                f"costmodel.ENGINE_VARIANTS layout {layout!r} not in "
                "knobs.TABLE_LAYOUTS"
            )
        if variant not in INSERT_VARIANTS:
            problems.append(
                f"costmodel.ENGINE_VARIANTS insert variant {variant!r} not "
                "in knobs.INSERT_VARIANTS"
            )
        if cost not in COST_VARIANTS:
            problems.append(
                f"costmodel.ENGINE_VARIANTS cost variant {cost!r} not in "
                "knobs.COST_VARIANTS"
            )

    # The warm-start seam (store/warm.py) is jax-free like this module:
    # probe its alias before the jax-importing engine block so even a
    # jax-free image catches a restated WARM_KINDS copy there.
    from .store import warm

    if warm.WARM_KINDS is not WARM_KINDS:
        problems.append(
            "store.warm.WARM_KINDS is a restated copy, not the "
            "knobs.WARM_KINDS alias"
        )

    # The URI dispatcher (faults/blobstore.py — jax-free like this module)
    # must dispatch over THE backend tuple: `backend_of` iterates
    # BLOB_BACKENDS[1:] as URI schemes, so a restated copy there would let
    # a new scheme land in one place and silently not the other.
    from .faults import blobstore

    if blobstore.BLOB_BACKENDS is not BLOB_BACKENDS:
        problems.append(
            "faults.blobstore.BLOB_BACKENDS is a restated copy, not the "
            "knobs.BLOB_BACKENDS alias"
        )
    for backend in BLOB_BACKENDS:
        probe = {
            "file": "/tmp/x", "blob": "blob://h:1/x",
            "s3": "s3://b/x", "gs": "gs://b/x",
        }[backend]
        if blobstore.backend_of(probe) != backend:
            problems.append(
                f"blobstore.backend_of does not round-trip backend "
                f"{backend!r} (probe {probe!r})"
            )

    try:
        from .parallel.sharded import ShardedSearch
        from .service.scheduler import ServiceEngine
        from .tensor import inserts
        from .tensor.frontier import FrontierSearch
        from .tensor.resident import ResidentSearch
        from .tensor.simulation import DeviceSimulation
    except ModuleNotFoundError as e:
        # jax-free images run the lint half only (`--skip-audit`); the
        # engine cross-check needs the jax-importing spine and is the one
        # probe that cannot run there.
        if e.name and e.name.split(".")[0] in ("jax", "jaxlib"):
            return problems
        raise

    # The dispatch table (tensor/inserts.py) must cover exactly this
    # registry's variant names — a variant registered here without a
    # dispatch entry (or vice versa) is the r10 drift class this module
    # exists to bound.
    if set(inserts.INSERT_TABLE) != set(INSERT_VARIANTS):
        problems.append(
            "inserts.INSERT_TABLE keys != knobs.INSERT_VARIANTS: "
            f"{sorted(inserts.INSERT_TABLE)} vs {sorted(INSERT_VARIANTS)}"
        )
    if not set(inserts.KV_INSERT_TABLE) <= set(INSERT_VARIANTS):
        problems.append(
            "inserts.KV_INSERT_TABLE names a variant outside "
            f"knobs.INSERT_VARIANTS: {sorted(inserts.KV_INSERT_TABLE)}"
        )
    # The engines must all dispatch through THE table, not a restated copy
    # (same alias-identity probe as the costmodel tuple above).
    if FrontierSearch.INSERT_VARIANTS is not inserts.INSERT_TABLE:
        problems.append(
            "FrontierSearch.INSERT_VARIANTS is a restated copy, not the "
            "inserts.INSERT_TABLE alias"
        )
    if ServiceEngine.INSERT_VARIANTS is not inserts.INSERT_TABLE:
        problems.append(
            "ServiceEngine.INSERT_VARIANTS is a restated copy, not the "
            "inserts.INSERT_TABLE alias"
        )
    # The fourth engine's dedup universe must be THE registry tuple (alias
    # identity, same probe as the costmodel alias above), and its shared
    # visited table must resolve through the one insert dispatch table.
    if DeviceSimulation.DEDUP_KINDS is not SIM_DEDUP_KINDS:
        problems.append(
            "DeviceSimulation.DEDUP_KINDS is a restated copy, not the "
            "knobs.SIM_DEDUP_KINDS alias"
        )
    # Corpus warm-start: every engine (and the service scheduler) must
    # alias the one WARM_KINDS tuple AND the one preload seam — a private
    # per-engine warm path is exactly the restatement ROADMAP item 4(c)
    # removed (the resident/sharded/simulation warm-start gap).
    for cls in (
        FrontierSearch, ResidentSearch, ShardedSearch, DeviceSimulation,
        ServiceEngine,
    ):
        if getattr(cls, "WARM_KINDS", None) is not WARM_KINDS:
            problems.append(
                f"{cls.__name__}.WARM_KINDS is a restated copy, not the "
                "knobs.WARM_KINDS alias"
            )
        if getattr(cls, "WARM_SEAM", None) is not warm:
            problems.append(
                f"{cls.__name__}.WARM_SEAM is not the store.warm module "
                "(the one warm-start/preload seam)"
            )
    return problems
