"""Self-healing supervisor: retry / degrade / checkpoint recovery loops.

`run_supervised(model, ...)` wraps any of the three device engines
(frontier / resident / sharded) in the crash-only discipline (Candea & Fox,
HotOS'03): the engine is allowed — expected — to die, and recovery is
always the same move: throw the instance away, reload the last good
checkpoint generation (atomic + CRC-verified, faults/ckptio.py), and
re-drive. The run is sliced into bounded-step chunks so there is always a
recent sound boundary to checkpoint, and BFS determinism makes the final
counts/discoveries bit-identical however many times the run was cut down
mid-flight.

Recovery policy, in order:

1. **Bounded retry with backoff** — retriable faults (injected `FaultError`s,
   `RuntimeError`/XLA errors, `OSError`) trigger an exponential backoff with
   deterministic jitter, then a restore-or-restart. Non-retriable errors
   (config/programming errors) propagate immediately.
2. **Targeted regrow** — overflow aborts ("hash table or queue full") grow
   the named resource through the engines' own checkpoint+regrow machinery
   instead of burning generic retries.
3. **Degrade ladder** — repeated failures at one rung escalate:
   retry-same-config → shrink batch K → enable (or widen) the tiered store
   → `JAX_PLATFORMS=cpu` as the last resort (effective for engines built
   after the switch; recorded either way).
4. **Watchdog** — each slice runs under a deadline; a hang is cancelled
   (injected hang gates) or abandoned (real ones) and converted into a
   retriable `WatchdogTimeout`.
5. **Graceful drain** — SIGTERM checkpoints the current boundary and
   returns the partial result instead of dying mid-write.

Every recovery event lands in the obs counter registry (source
"supervisor"), in spans via `tracer`, and in the returned
`SearchResult.detail["faults"]` under the documented schema
(obs/schema.py: FAULTS_DETAIL_KEYS).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..core.discovery import HasDiscoveries
from ..obs import REGISTRY, as_tracer
from .ckptio import CheckpointCorrupt, latest_generation
from .plan import (
    FaultError,
    FaultPlan,
    WatchdogTimeout,
    active,
    deterministic_backoff,
)

ENGINES = ("frontier", "resident", "sharded")

#: Degrade ladder rung names, in escalation order.
RUNGS = ("retry", "shrink_batch", "tiered", "cpu")


@dataclass
class SupervisorConfig:
    """Knobs for `run_supervised`. Defaults suit unattended production
    runs; tests shrink the timers to keep the suite fast."""

    max_retries: int = 8  # total fault budget before giving up
    retries_per_rung: int = 2  # consecutive failures before escalating
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_cap_s: float = 2.0
    checkpoint_every_steps: int = 512  # slice size == checkpoint cadence
    checkpoint_interval_s: float = 0.0  # min seconds between generations
    watchdog_s: Optional[float] = None  # slice deadline (None = no watchdog)
    watchdog_grace_s: float = 1.0  # wait after cancelling a hang gate
    # Extra watchdog allowance for the FIRST slice of each engine build:
    # every fresh instance recompiles its step kernels (per-instance jit
    # closures), and compile time is progress, not a hang.
    compile_grace_s: float = 300.0
    min_batch: int = 64  # shrink_batch floor
    drain_on_sigterm: bool = True
    seed: int = 0  # jitter determinism


class SupervisorGaveUp(RuntimeError):
    """The fault budget ran out; the last underlying failure is chained."""


class Supervisor:
    """One supervised run. Use `run_supervised` unless you need to poke at
    the counters mid-flight."""

    def __init__(
        self,
        model,
        engine: str = "resident",
        plan: Optional[FaultPlan] = None,
        config: Optional[SupervisorConfig] = None,
        checkpoint_path: Optional[str] = None,
        engine_kwargs: Optional[dict] = None,
        run_kwargs: Optional[dict] = None,
        tracer=None,
    ):
        if engine not in ENGINES:
            raise ValueError(f"engine must be one of {ENGINES}, got {engine!r}")
        self.model = model
        self.engine = engine
        self.plan = plan if plan is not None else FaultPlan.from_env()
        self.cfg = config or SupervisorConfig()
        self.ckpt = checkpoint_path
        self.engine_kwargs = dict(engine_kwargs or {})
        self.run_kwargs = dict(run_kwargs or {})
        for k in ("budget", "max_steps", "progress"):
            if k in self.run_kwargs:
                raise ValueError(
                    f"run_kwargs[{k!r}] is owned by the supervisor "
                    "(it slices the run itself)"
                )
        self._tracer = as_tracer(tracer)
        if self.plan is not None and self.plan.tracer is None:
            self.plan.tracer = self._tracer
        # Mutable config the degrade ladder rewrites between builds.
        self._batch = self.engine_kwargs.pop("batch_size", 1024)
        self._table_log2 = self.engine_kwargs.pop("table_log2", 20)
        self._queue_log2: Optional[int] = self.engine_kwargs.pop(
            "queue_log2", None
        )
        self._grow_table = False  # pass table_log2 to the next restore
        self._grow_queue = False
        self.counters = {
            "retries": 0,
            "backoff_ms": 0,
            "degrade_steps": 0,
            "degrade_rung": 0,
            "checkpoint_generations": 0,
            "restores": 0,
            "watchdog_fired": 0,
            "drained": 0,
        }
        self._rung = 0
        self._rung_failures = 0
        self._eng_warm = False  # current engine has completed >= 1 slice
        self._sigterm = False
        self._last_ckpt_t = 0.0
        self._metrics_name = REGISTRY.register("supervisor", self.metrics)

    # -- engine lifecycle ------------------------------------------------------

    def _fresh(self):
        kw = dict(
            self.engine_kwargs,
            batch_size=self._batch,
            table_log2=self._table_log2,
        )
        if self.engine == "frontier":
            from ..tensor.frontier import FrontierSearch

            return FrontierSearch(self.model, **kw)
        if self.engine == "resident":
            from ..tensor.resident import ResidentSearch

            if self._queue_log2 is not None:
                kw["queue_log2"] = self._queue_log2
            return ResidentSearch(self.model, **kw)
        from ..parallel.sharded import ShardedSearch

        return ShardedSearch(self.model, **kw)

    def _restore(self):
        """Rebuild from the newest intact checkpoint generation, or None
        when no restore is possible (caller falls back to a fresh build)."""
        if self.ckpt is None or latest_generation(self.ckpt) is None:
            return None
        try:
            if self.engine == "frontier":
                if self._grow_table or self._grow_queue:
                    # FrontierSearch.load_checkpoint cannot resize; a grown
                    # run restarts fresh at the new size instead.
                    return None
                from ..tensor.frontier import FrontierSearch

                eng = FrontierSearch.load_checkpoint(
                    self.model, self.ckpt, batch_size=self._batch
                )
            elif self.engine == "resident":
                from ..tensor.resident import ResidentSearch

                kw: dict = {"batch_size": self._batch}
                if self._grow_table:
                    kw["table_log2"] = self._table_log2
                if self._grow_queue and self._queue_log2 is not None:
                    kw["queue_log2"] = self._queue_log2
                eng = ResidentSearch.load_checkpoint(self.model, self.ckpt, **kw)
            else:
                from ..parallel.sharded import ShardedSearch

                kw = {"batch_size": self._batch}
                if "mesh" in self.engine_kwargs:
                    kw["mesh"] = self.engine_kwargs["mesh"]
                if self._grow_table:
                    kw["table_log2"] = self._table_log2
                eng = ShardedSearch.load_checkpoint(self.model, self.ckpt, **kw)
        except CheckpointCorrupt:
            return None
        self._grow_table = self._grow_queue = False
        self.counters["restores"] += 1
        self._tracer.instant("supervisor.restore", cat="faults")
        return eng

    def _build(self):
        eng = self._restore()
        if eng is None:
            eng = self._fresh()
        return eng

    def _checkpoint(self, eng, force: bool = False) -> None:
        if self.ckpt is None:
            return
        now = time.monotonic()
        if not force and now - self._last_ckpt_t < self.cfg.checkpoint_interval_s:
            return
        try:
            with self._tracer.span("supervisor.checkpoint", cat="faults"):
                eng.checkpoint(self.ckpt)
        except RuntimeError:
            # "nothing to checkpoint" (no carry yet / vacuous finish):
            # there is no progress to protect, so nothing is lost.
            return
        self.counters["checkpoint_generations"] += 1
        self._last_ckpt_t = now

    # -- slicing ---------------------------------------------------------------

    def _engine_steps(self, eng) -> int:
        import numpy as np

        carry = getattr(eng, "_carry", None)
        if carry is None:
            return 0
        return int(np.max(np.asarray(carry.steps)))

    def _slice(self, eng):
        """Drive the engine for at most checkpoint_every_steps steps."""
        B = self.cfg.checkpoint_every_steps
        if self.engine == "frontier":
            return eng.run(max_steps=B, **self.run_kwargs)
        steps0 = self._engine_steps(eng)
        return eng.run(budget=B, max_steps=steps0 + B, **self.run_kwargs)

    def _slice_watched(self, eng):
        """Run one slice under the watchdog deadline: a slice that neither
        finishes nor faults in time is cancelled (injected hang gates) or
        abandoned (real hangs) and surfaced as a retriable fault."""
        if self.cfg.watchdog_s is None:
            return self._slice(eng)
        box: list = []

        def work():
            try:
                box.append(("ok", self._slice(eng)))
            except BaseException as e:  # noqa: BLE001 — re-raised by caller
                box.append(("err", e))

        deadline = self.cfg.watchdog_s
        if not self._eng_warm:
            deadline += self.cfg.compile_grace_s
        t = threading.Thread(target=work, daemon=True)
        t.start()
        t.join(deadline)
        if t.is_alive():
            self.counters["watchdog_fired"] += 1
            self._tracer.instant("supervisor.watchdog", cat="faults")
            if self.plan is not None:
                self.plan.cancel_hangs()
            t.join(self.cfg.watchdog_grace_s)
            if t.is_alive():
                # A real hang: abandon the worker (daemon) and rebuild from
                # the last checkpoint; the stuck engine object is dropped.
                raise WatchdogTimeout(
                    f"slice exceeded watchdog_s={self.cfg.watchdog_s}; "
                    "engine abandoned"
                )
        status, val = box[0]
        if status == "err":
            raise val
        return val

    # -- completion / policy ---------------------------------------------------

    def _policy_done(self, result) -> bool:
        props = self.model.properties()
        fw = self.run_kwargs.get("finish_when", HasDiscoveries.ALL)
        disc = set(result.discoveries)
        if props and len(disc) == len(props):
            return True
        if fw.matches(props, disc):
            return True
        tsc = self.run_kwargs.get("target_state_count")
        if tsc is not None and result.state_count >= tsc:
            return True
        return False

    def _done(self, eng, result) -> bool:
        if result.complete or self._policy_done(result):
            return True
        if self.engine == "frontier" and not getattr(eng, "_q", True):
            return True
        return False

    # -- failure handling ------------------------------------------------------

    @staticmethod
    def _retriable(e: BaseException) -> bool:
        return isinstance(e, (FaultError, RuntimeError, OSError))

    @staticmethod
    def _overflow_kind(e: BaseException) -> Optional[str]:
        msg = str(e)
        if "queue full" in msg:
            return "queue"
        if "table full" in msg or "table or queue full" in msg:
            return "table"
        return None

    def _backoff(self, attempt: int) -> None:
        # The ONE seeded backoff spelling (faults/plan.py), shared with
        # the fleet router's submit retries and the blob-store client.
        delay = deterministic_backoff(
            self.cfg.seed, "backoff", attempt,
            self.cfg.backoff_base_s, self.cfg.backoff_cap_s,
            factor=self.cfg.backoff_factor,
        )
        if delay <= 0:
            return
        self.counters["backoff_ms"] += int(delay * 1000)
        time.sleep(delay)

    def _degrade(self) -> None:
        """Escalate one rung of the ladder and rewrite the config the next
        engine build will use."""
        if self._rung >= len(RUNGS) - 1:
            return
        self._rung += 1
        self._rung_failures = 0
        self.counters["degrade_steps"] += 1
        self.counters["degrade_rung"] = self._rung
        rung = RUNGS[self._rung]
        self._tracer.instant("supervisor.degrade", cat="faults", rung=rung)
        if rung == "shrink_batch":
            # Halve toward the floor, but never GROW a batch that already
            # sits below min_batch (a tiny batch may be what makes the
            # user's table config valid at all).
            self._batch = max(self._batch // 2, min(self._batch, self.cfg.min_batch))
        elif rung == "tiered":
            if self.engine_kwargs.get("store") == "tiered":
                # Already tiered: widen the spill band instead.
                hw = self.engine_kwargs.get("high_water", 0.85)
                self.engine_kwargs["high_water"] = max(hw - 0.15, 0.3)
            else:
                self.engine_kwargs["store"] = "tiered"
            # A store change cannot ride a checkpoint resume (the store
            # config travels in checkpoint meta); restart fresh.
            self._drop_checkpoint()
        elif rung == "cpu":
            # Last resort. The env var covers worker subprocesses and any
            # jax not yet initialized; jax.config.update is the in-process
            # attempt — best-effort, because a backend that has already
            # served a computation may be pinned for the process lifetime
            # (in which case this rung is recorded as attempted and the
            # remaining retries run on the original platform).
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                import jax

                jax.config.update("jax_platforms", "cpu")
            except Exception:  # noqa: BLE001 — backend already pinned
                pass
            self._drop_checkpoint()

    def _drop_checkpoint(self) -> None:
        if self.ckpt is None:
            return
        from .ckptio import normalize_ckpt_path

        p = normalize_ckpt_path(self.ckpt)
        for f in (p, p + ".prev"):
            try:
                os.remove(f)
            except OSError:
                pass

    # -- the supervised loop ---------------------------------------------------

    def run(self):
        """Drive the search to completion (or graceful drain); returns the
        engine's `SearchResult` with `detail["faults"]` merged in."""
        old_handler = None
        in_main = threading.current_thread() is threading.main_thread()
        if self.cfg.drain_on_sigterm and in_main:
            try:
                old_handler = signal.signal(
                    signal.SIGTERM, lambda *_: setattr(self, "_sigterm", True)
                )
            except ValueError:
                old_handler = None
        try:
            with active(self.plan):
                return self._run_supervised()
        finally:
            if old_handler is not None:
                signal.signal(signal.SIGTERM, old_handler)

    def _run_supervised(self):
        failures = 0
        eng = None
        result = None
        while True:
            if eng is None:
                eng = self._build()
                self._eng_warm = False
            try:
                with self._tracer.span("supervisor.slice", cat="faults"):
                    result = self._slice_watched(eng)
            except BaseException as e:  # noqa: BLE001 — classified below
                if not self._retriable(e):
                    raise
                failures += 1
                self._rung_failures += 1
                self.counters["retries"] += 1
                self._tracer.instant(
                    "supervisor.retry",
                    cat="faults",
                    error=type(e).__name__,
                    failures=failures,
                )
                if failures > self.cfg.max_retries:
                    raise SupervisorGaveUp(
                        f"fault budget exhausted after {failures} failures "
                        f"(last: {type(e).__name__}: {e})"
                    ) from e
                overflow = self._overflow_kind(e)
                if overflow is not None:
                    # Targeted regrow: checkpoint the reverted carry (the
                    # chunked engines keep it at the last sound boundary)
                    # and grow the resource that actually ran out.
                    if overflow == "table":
                        self._table_log2 += 1
                        self._grow_table = True
                    else:
                        self._queue_log2 = (
                            self._queue_log2 or self._table_log2
                        ) + 1
                        self._grow_queue = True
                    if self.engine != "frontier" and getattr(
                        eng, "_carry", None
                    ) is not None:
                        self._checkpoint(eng, force=True)
                elif self._rung_failures >= self.cfg.retries_per_rung:
                    self._degrade()
                self._backoff(failures - 1)
                eng = None  # crash-only: discard and rebuild
                continue
            # Slice succeeded: progress resets the per-rung failure streak.
            self._rung_failures = 0
            self._eng_warm = True
            if self._done(eng, result):
                self._checkpoint(eng)
                break
            if self._sigterm:
                self.counters["drained"] += 1
                self._tracer.instant("supervisor.drain", cat="faults")
                self._checkpoint(eng, force=True)
                break
            self._checkpoint(eng)
        return dataclasses.replace(
            result,
            detail={**(result.detail or {}), "faults": self.fault_stats()},
        )

    # -- reporting -------------------------------------------------------------

    def fault_stats(self) -> dict:
        """The `detail["faults"]` dict (obs/schema.py FAULTS_DETAIL_KEYS)."""
        out = (
            self.plan.stats()
            if self.plan is not None
            else {"injected_total": 0, "injected": {}}
        )
        out.update(self.counters)
        return out

    def metrics(self) -> dict:
        """Flat counters for the obs registry / `GET /metrics`."""
        return self.fault_stats()


def run_supervised(
    model,
    engine: str = "resident",
    plan: Optional[FaultPlan] = None,
    config: Optional[SupervisorConfig] = None,
    checkpoint_path: Optional[str] = None,
    engine_kwargs: Optional[dict] = None,
    run_kwargs: Optional[dict] = None,
    tracer=None,
):
    """Run `model` under the self-healing supervisor; see the module
    docstring for the recovery policy. `plan` defaults to
    `FaultPlan.from_env()` (the `SR_TPU_FAULTS=` knob); pass
    `checkpoint_path` to enable checkpoint-based recovery (strongly
    recommended — without it every recovery is a fresh restart)."""
    return Supervisor(
        model,
        engine=engine,
        plan=plan,
        config=config,
        checkpoint_path=checkpoint_path,
        engine_kwargs=engine_kwargs,
        run_kwargs=run_kwargs,
        tracer=tracer,
    ).run()
