"""Chaos plane + self-healing supervisor (the robustness subsystem).

Two halves:

1. **Chaos plane** (`plan.py`) — a seeded, deterministic `FaultPlan`
   (config object + ``SR_TPU_FAULTS=`` env) with named injection points
   threaded through every failure boundary the checker already has: engine
   step dispatch, tiered-store spill/resolution, sharded per-shard
   transfers, checkpoint writes, service job steps, and the HTTP front end.
2. **Supervisor** (`supervisor.py`) — `run_supervised(...)` wraps the
   engines with periodic atomic checkpointing (`ckptio.py`: tmp+fsync+
   rename, CRC32 footer, generation fallback), bounded retry with
   deterministic backoff, a degrade ladder, a watchdog that converts hangs
   into retriable faults, and graceful SIGTERM drain. Service hardening
   (per-group failure isolation + poison-job quarantine) lives in
   stateright_tpu/service/.

Recovery events register into the obs counter registry and appear in
`SearchResult.detail["faults"]` (schema: obs/schema.py FAULTS_DETAIL_KEYS).
"""

from .blobstore import (
    BlobUnavailable,
    blob_backend,
    is_blob_uri,
    normalize_root,
    serve_blobd,
)
from .ckptio import (
    CheckpointCorrupt,
    atomic_savez,
    latest_generation,
    load_latest,
    normalize_ckpt_path,
    read_verified,
)
from .plan import (
    KINDS,
    DeviceOOM,
    FaultError,
    FaultPlan,
    FaultRule,
    HttpFault,
    PoisonFault,
    PreemptionFault,
    ReplicaCrash,
    ShardFault,
    SpillIOError,
    WatchdogTimeout,
    XlaError,
    active,
    active_plan,
    install_plan,
    maybe_fault,
)
from .supervisor import (
    RUNGS,
    Supervisor,
    SupervisorConfig,
    SupervisorGaveUp,
    run_supervised,
)

__all__ = [
    "FaultPlan",
    "FaultRule",
    "FaultError",
    "DeviceOOM",
    "XlaError",
    "PreemptionFault",
    "SpillIOError",
    "ShardFault",
    "PoisonFault",
    "HttpFault",
    "ReplicaCrash",
    "WatchdogTimeout",
    "KINDS",
    "maybe_fault",
    "install_plan",
    "active_plan",
    "active",
    "atomic_savez",
    "read_verified",
    "load_latest",
    "latest_generation",
    "normalize_ckpt_path",
    "CheckpointCorrupt",
    "BlobUnavailable",
    "blob_backend",
    "is_blob_uri",
    "normalize_root",
    "serve_blobd",
    "Supervisor",
    "SupervisorConfig",
    "SupervisorGaveUp",
    "RUNGS",
    "run_supervised",
]
