"""Pluggable blob-store backend for the checkpoint plane (ROADMAP item 1:
the true multi-host residue).

Everything durable the fleet shares — checkpoint generations, lease
records, corpus entries, member-discovery records, synced journals — is
bytes-at-a-name with one-generation history. On one machine that name is a
filesystem path and the discipline is tmp+fsync+rename (faults/ckptio.py);
across machines it is an OBJECT STORE, where the failure modes are
throttling (429/5xx), latency, partial writes, and stale listings rather
than torn renames. This module gives the repo ONE backend seam for both:

- `LocalFSBlobStore` — today's on-disk layout, bit-identical: files under
  a root directory, `put` staged through a pid-unique tmp + fsync +
  `os.replace`, the previous generation rotated to ``<name>.prev``.
- `HTTPBlobStore` / `_BlobClient` — an HTTP object-store client with
  conditional-put (``If-None-Match: *``) and server-side generation
  tokens, speaking to the emulator in this module (`serve_blobd`, also
  runnable standalone as ``scripts/blobd.py``). The server rotates the
  previous payload to ``<name>.prev`` atomically on PUT — the same
  two-generation contract as the filesystem, so `ckptio.load_latest`'s
  current-then-`.prev` walk is backend-agnostic.

Backends are chosen by ROOT URI: a plain path or ``file://...`` is the
filesystem; ``blob://host:port[/prefix]`` is the HTTP store. `faults/
ckptio.py` (`fenced_savez`/`fenced_load_latest`), `service/lease.py`, and
`store/corpus.py` all route through here when handed a blob URI, so one
shared root URI is the fleet's whole storage configuration.

**Chaos + retry discipline**: every HTTP op is a chaos boundary
(``blob.put`` / ``blob.get`` / ``blob.list`` / ``blob.delete`` in
faults/plan.py) and runs
under bounded retry with the supervisor's seeded deterministic backoff and
a per-op deadline. Injected 429/5xx/transport faults are retried and
counted; a ``torn`` PUT truncates the uploaded payload (CRC-rejected at
read, ``.prev`` serves — the r13 torn-generation story over the network);
a ``stale`` LIST serves the previous listing (consumers degrade to a
bigger directory, never a wrong result); ``slow`` injects latency. Retry
exhaustion raises `BlobUnavailable` (an OSError), which every caller
already degrades on: resume-fresh, cold corpus run, counted publish fault.
Counters are exported through the obs REGISTRY "blob" source.

The ONE sanctioned write path into a blob store is `faults/ckptio.py`
(`fenced_savez` / `write_record`) — srlint SR002 flags a bare ``put``
anywhere else, exactly as it flags a bare `atomic_savez`: a write that
skips the seam also skips the CRC footer and the lease stamp.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import namedtuple
from typing import Optional

from .plan import (
    FaultError,
    active_plan,
    deterministic_backoff,
    maybe_fault,
)

__all__ = [
    "BlobStat",
    "BlobUnavailable",
    "HTTPBlobStore",
    "LocalFSBlobStore",
    "blob_backend",
    "is_blob_uri",
    "normalize_root",
    "serve_blobd",
]

#: One listing row, backend-agnostic: `name` is relative to the store's
#: root, `mtime` is the backend's last-write stamp (file mtime / server
#: PUT time) — the metadata `CorpusStore.gc`'s LRU order runs on.
BlobStat = namedtuple("BlobStat", "name size mtime")


class BlobUnavailable(OSError):
    """A blob op exhausted its bounded retry / per-op deadline. An OSError
    so every existing degrade path (resume-fresh, cold corpus, counted
    publish fault) absorbs it without new handling."""


class _Conflict(RuntimeError):
    """Server refused a conditional put (If-None-Match/If-Match miss) —
    internal; `put(if_absent=True)` surfaces it as a None return."""


#: HTTP statuses worth retrying (throttling + transient server failures).
RETRYABLE_HTTP = (429, 500, 502, 503, 504)

#: Injected-latency sleep for a consumed ``slow`` fault, seconds.
SLOW_S = 0.05


def is_blob_uri(path) -> bool:
    return isinstance(path, str) and path.startswith("blob://")


def normalize_root(root: Optional[str]) -> Optional[str]:
    """Strip a ``file://`` scheme down to the plain path it names (so
    everything downstream sees either a filesystem path or a ``blob://``
    URI — the only two spellings the backend seam dispatches on)."""
    if isinstance(root, str) and root.startswith("file://"):
        return root[len("file://"):] or "/"
    return root


def split_blob_uri(uri: str) -> tuple:
    """``blob://host:port/some/name`` -> ("http://host:port", "/some/name")."""
    rest = uri[len("blob://"):]
    host, slash, name = rest.partition("/")
    if not host:
        raise ValueError(f"blob URI {uri!r} has no host")
    return f"http://{host}", ("/" + name if slash else "/")


# -- the HTTP client (absolute names, shared per server) -----------------------


class _BlobClient:
    """One server's client: retry/backoff/chaos wrapper over the four
    verbs, counters exported through the obs REGISTRY "blob" source.
    Cached per base URL (`_client`) so every URI op against one server
    shares one counter set and one stale-list cache."""

    retry_limit = 4
    op_deadline_s = 30.0
    backoff_base_s = 0.02
    backoff_cap_s = 0.5

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")
        self._lock = threading.Lock()
        self._stale_cache: dict = {}  # prefix -> previous listing
        self.counters = {
            "ops": 0,
            "retries": 0,
            "backoff_ms": 0,
            "faults": 0,
            "torn_puts": 0,
            "stale_lists": 0,
            "slow_ops": 0,
            "unavailable": 0,
        }
        from ..obs import REGISTRY

        self._metrics_name = REGISTRY.register("blob", self.metrics)

    def metrics(self) -> dict:
        with self._lock:
            return dict(self.counters)

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    # -- retry/chaos wrapper ---------------------------------------------------

    def _op(
        self,
        point: str,
        fn,
        chaos: bool = True,
        deadline_s: Optional[float] = None,
        **ctx,
    ):
        """Run one server round trip under the chaos point + bounded
        deterministic-backoff retry + per-op deadline. 404s and
        conditional-put conflicts pass straight through (they are answers,
        not failures); everything transport-shaped is retried until the
        budget runs out, then surfaced as `BlobUnavailable`.

        `chaos=False` skips the injection point (real transport failures
        are still retried): reserved for ops the chaos plane itself can
        re-enter — the flight-recorder journal's blob mirror, where an
        injected fault would be recorded as a `fault.injected` event into
        the very journal whose sync is mid-flight (journal `_io_lock` and
        plan lock re-entered: a self-deadlock, found by the smoke's blob
        partition phase)."""
        self._count("ops")
        plan = active_plan() if chaos else None
        if plan is not None and plan.consume_special(point, "slow"):
            self._count("slow_ops")
            time.sleep(SLOW_S)
        seed = plan.seed if plan is not None else 0
        deadline = time.monotonic() + (
            deadline_s if deadline_s is not None else self.op_deadline_s
        )
        attempt = 0
        last: Optional[BaseException] = None
        while True:
            try:
                if chaos:
                    maybe_fault(point, store=self.base_url, **ctx)
                return fn()
            except (FileNotFoundError, _Conflict):
                raise
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    raise FileNotFoundError(
                        f"{self.base_url}: no such blob ({ctx})"
                    ) from e
                if e.code == 412:
                    raise _Conflict(str(e)) from e
                if e.code not in RETRYABLE_HTTP:
                    raise BlobUnavailable(
                        f"blob op {point} failed with HTTP {e.code}"
                    ) from e
                last = e
            except (
                FaultError,
                urllib.error.URLError,
                ConnectionError,
                TimeoutError,
                http.client.HTTPException,
                OSError,
            ) as e:
                last = e
            self._count("faults")
            attempt += 1
            if attempt > self.retry_limit or time.monotonic() >= deadline:
                self._count("unavailable")
                raise BlobUnavailable(
                    f"blob op {point} against {self.base_url} exhausted "
                    f"{attempt} attempts (last: {type(last).__name__}: "
                    f"{last})"
                ) from last
            delay = deterministic_backoff(
                seed, f"{point}.backoff", attempt - 1,
                self.backoff_base_s, self.backoff_cap_s,
            )
            delay = min(delay, max(deadline - time.monotonic(), 0.0))
            self._count("retries")
            self._count("backoff_ms", int(delay * 1000))
            time.sleep(delay)

    # -- raw verbs -------------------------------------------------------------

    def _url(self, name: str) -> str:
        return self.base_url + "/b" + urllib.parse.quote(name)

    def _request(self, req, timeout: float = 10.0):
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()

    def put(
        self,
        name: str,
        data: bytes,
        rotate: bool = True,
        if_absent: bool = False,
        chaos: bool = True,
        deadline_s: Optional[float] = None,
    ) -> Optional[int]:
        """Upload one blob; the server rotates the previous payload to
        ``<name>.prev`` when `rotate` (the two-generation contract).
        `if_absent=True` is the conditional put (``If-None-Match: *``):
        None means another writer got there first — the content-addressed
        idempotence the corpus publish rides. A consumed ``torn`` fault
        truncates the payload BEFORE upload: the partial PUT the read-side
        CRC must reject. `chaos=False` (journal mirror only) skips the
        injection point — see `_op`; `deadline_s` overrides the per-op
        deadline (best-effort callers cap their stall).

        Returns the server's generation token — NEGATED when the upload
        was torn, so the caller KNOWS this write is not trustworthy
        (ckptio must not mark the path written-intact, or a later write
        would rotate the torn generation over the good `.prev`, and a
        conditional republish would 412-skip the repair forever)."""
        plan = active_plan() if chaos else None
        torn = False
        if plan is not None and plan.consume_special("blob.put", "torn"):
            self._count("torn_puts")
            data = data[: max(len(data) // 2, 1)]
            torn = True

        def do():
            headers = {"Content-Type": "application/octet-stream"}
            if if_absent:
                headers["If-None-Match"] = "*"
            req = urllib.request.Request(
                self._url(name) + f"?rotate={int(bool(rotate))}",
                data=data,
                method="PUT",
                headers=headers,
            )
            out = json.loads(self._request(req) or b"{}")
            return int(out.get("generation", 0))

        try:
            gen = self._op(
                "blob.put", do, chaos=chaos, deadline_s=deadline_s,
                name=name[-64:],
            )
        except _Conflict:
            return None
        return -gen if torn and gen else gen

    def get(self, name: str) -> bytes:
        """One blob's bytes; FileNotFoundError when absent (an answer, not
        a failure — never retried)."""

        def do():
            return self._request(urllib.request.Request(self._url(name)))

        return self._op("blob.get", do, name=name[-64:])

    def delete(self, name: str) -> bool:
        # Its own chaos point: deletes riding ``blob.put`` would shift
        # the put hit counter (replayed torn-put plans landing on the
        # wrong upload) and let put-targeted rules fire on GC traffic.
        def do():
            req = urllib.request.Request(self._url(name), method="DELETE")
            out = json.loads(self._request(req) or b"{}")
            return bool(out.get("deleted"))

        return self._op("blob.delete", do, name=name[-64:])

    def list(self, prefix: str = "/") -> list:
        """Every blob under `prefix` as `BlobStat` rows (absolute names).
        A consumed ``stale`` fault serves the PREVIOUS listing for this
        prefix — the eventually-consistent LIST every consumer must
        tolerate (GC sweeps a smaller set, discovery sees yesterday's
        members; both degrade, neither is wrong)."""
        plan = active_plan()
        if plan is not None and plan.consume_special("blob.list", "stale"):
            self._count("stale_lists")
            return list(self._stale_cache.get(prefix, ()))

        def do():
            req = urllib.request.Request(
                self.base_url
                + "/list?prefix="
                + urllib.parse.quote(prefix)
            )
            out = json.loads(self._request(req) or b"{}")
            return [
                BlobStat(b["name"], int(b["size"]), float(b["mtime"]))
                for b in out.get("blobs", ())
            ]

        out = self._op("blob.list", do, prefix=prefix[-64:])
        self._stale_cache[prefix] = list(out)
        return out

    def exists(self, name: str) -> bool:
        """Existence probe via HEAD — answers without downloading the
        payload (checkpoint generations are multi-MB; `any_generation`
        probes two names per corpus lookup). Runs with `chaos=False`:
        letting HEADs consume ``blob.get`` hits would shift the point's
        hit numbering and break replayed plans (the same reason deletes
        got their own point), and the payload GET that always follows a
        positive probe is the real chaos surface anyway."""

        def do():
            req = urllib.request.Request(self._url(name), method="HEAD")
            self._request(req)
            return True

        try:
            return bool(
                self._op("blob.get", do, chaos=False, name=name[-64:])
            )
        except (FileNotFoundError, BlobUnavailable):
            return False


_clients: dict = {}
_clients_lock = threading.Lock()


def _client(base_url: str) -> _BlobClient:
    with _clients_lock:
        c = _clients.get(base_url)
        if c is None:
            c = _clients[base_url] = _BlobClient(base_url)
        return c


# -- URI-level helpers (what ckptio routes through) ----------------------------


def uri_client(uri: str) -> tuple:
    """(client, absolute name) for one ``blob://`` URI."""
    base, name = split_blob_uri(uri)
    return _client(base), name


def get_blob(uri: str) -> bytes:
    c, name = uri_client(uri)
    return c.get(name)


def put_blob(
    uri: str,
    data: bytes,
    rotate: bool = True,
    if_absent: bool = False,
    chaos: bool = True,
    deadline_s: Optional[float] = None,
) -> Optional[int]:
    c, name = uri_client(uri)
    return c.put(
        name, data, rotate=rotate, if_absent=if_absent, chaos=chaos,
        deadline_s=deadline_s,
    )


def delete_blob(uri: str) -> bool:
    c, name = uri_client(uri)
    return c.delete(name)


def blob_exists(uri: str) -> bool:
    c, name = uri_client(uri)
    return c.exists(name)


# -- rooted store views (the corpus-GC / discovery listing seam) ---------------


class LocalFSBlobStore:
    """The filesystem backend behind the same four-verb surface: files
    under `root`, put through the pid-unique tmp + fsync + `os.replace`
    discipline with ``.prev`` rotation — byte-identical to what
    `ckptio.atomic_savez` leaves on disk, which is why routing `gc`/
    listing consumers through this view changes nothing on local roots."""

    def __init__(self, root: str):
        self.root = root

    def _path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def list(self, prefix: str = "") -> list:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for n in sorted(names):
            if prefix and not n.startswith(prefix):
                continue
            try:
                st = os.stat(self._path(n))
            except OSError:
                continue
            if not os.path.isfile(self._path(n)):
                continue
            out.append(BlobStat(n, int(st.st_size), float(st.st_mtime)))
        return out

    def get(self, name: str) -> bytes:
        with open(self._path(name), "rb") as f:
            return f.read()

    def put(
        self,
        name: str,
        data: bytes,
        rotate: bool = True,
        if_absent: bool = False,
    ) -> Optional[int]:
        path = self._path(name)
        if if_absent and os.path.exists(path):
            return None
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:  # srlint: ckpt-ok the LocalFS blob backend IS the sanctioned tmp/fsync/rename writer (rotation below)
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if rotate and os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
        # Make the renames themselves durable (best-effort: not every
        # filesystem supports directory fsync).
        try:
            dfd = os.open(self.root or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        return 1

    def delete(self, name: str) -> bool:
        try:
            os.unlink(self._path(name))
            return True
        except OSError:
            return False

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))


class HTTPBlobStore:
    """A rooted view over one server's `_BlobClient`: names are relative
    to the root URI's prefix, so `CorpusStore.gc` / discovery listings run
    the same code on both backends."""

    def __init__(self, root_uri: str):
        base, prefix = split_blob_uri(root_uri)
        if not prefix.endswith("/"):
            prefix += "/"
        self.root = root_uri
        self._c = _client(base)
        self._prefix = prefix

    def list(self, prefix: str = "") -> list:
        out = self._c.list(self._prefix + prefix)
        cut = len(self._prefix)
        return [BlobStat(b.name[cut:], b.size, b.mtime) for b in out]

    def get(self, name: str) -> bytes:
        return self._c.get(self._prefix + name)

    def put(
        self,
        name: str,
        data: bytes,
        rotate: bool = True,
        if_absent: bool = False,
    ) -> Optional[int]:
        return self._c.put(
            self._prefix + name, data, rotate=rotate, if_absent=if_absent
        )

    def delete(self, name: str) -> bool:
        return self._c.delete(self._prefix + name)

    def exists(self, name: str) -> bool:
        return self._c.exists(self._prefix + name)


def blob_backend(root: str):
    """The rooted store view for one root URI — `HTTPBlobStore` for
    ``blob://``, `LocalFSBlobStore` for a plain/‌``file://`` path. The ONE
    dispatch every backend-agnostic consumer (corpus GC, member
    discovery, journal-root listing) goes through."""
    root = normalize_root(root)
    if is_blob_uri(root):
        return HTTPBlobStore(root)
    return LocalFSBlobStore(root)


# -- the emulator server -------------------------------------------------------


class _ServerHandle:
    """serve_blobd's return: the bound address, the live store dict (tests
    reach in to corrupt/inspect payloads), and shutdown."""

    def __init__(self, httpd, store, thread):
        self.httpd = httpd
        self.store = store
        self.thread = thread

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"{host}:{port}"

    @property
    def root_uri(self) -> str:
        return f"blob://{self.address}"

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.thread is not None:
            self.thread.join(timeout=5.0)


def serve_blobd(address: str = "localhost:0", block: bool = False):
    """The in-proc HTTP object-store emulator (`scripts/blobd.py` runs it
    standalone). Protocol — deliberately the S3/GCS-shaped minimum:

    - ``PUT /b/<name>?rotate=0|1`` — store bytes; ``rotate=1`` moves the
      previous payload to ``<name>.prev`` atomically first (the
      two-generation contract). ``If-None-Match: *`` is the conditional
      put (412 when the name exists); ``If-Match: <gen>`` compares
      against the server's generation token. Returns ``{"generation": g}``.
    - ``GET /b/<name>`` — the bytes (+ ``X-Blob-Generation``); 404 absent.
    - ``DELETE /b/<name>`` — ``{"deleted": bool}``.
    - ``GET /list?prefix=`` — ``{"blobs": [{name,size,mtime,generation}]}``.
    - ``GET /healthz`` — liveness.

    Storage is in-memory (an emulator, not a database): one dict guarded
    by a lock, rotation + conditional checks atomic under it.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    store: dict = {}  # name -> {"data": bytes, "gen": int, "mtime": float}
    lock = threading.Lock()
    gen_counter = [0]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _name(self) -> Optional[str]:
            path = urllib.parse.unquote(self.path.partition("?")[0])
            if not path.startswith("/b/"):
                return None
            return path[len("/b"):]

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                with lock:
                    self._json({"ok": 1, "blobs": len(store)})
                return
            if path == "/list":
                q = urllib.parse.parse_qs(query)
                prefix = urllib.parse.unquote(q.get("prefix", [""])[0])
                with lock:
                    blobs = [
                        {
                            "name": n,
                            "size": len(rec["data"]),
                            "mtime": rec["mtime"],
                            "generation": rec["gen"],
                        }
                        for n, rec in sorted(store.items())
                        if n.startswith(prefix)
                    ]
                self._json({"blobs": blobs})
                return
            name = self._name()
            with lock:
                rec = store.get(name) if name else None
                data = rec["data"] if rec else None
                gen = rec["gen"] if rec else 0
            if data is None:
                self._json({"error": "no such blob"}, 404)
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-Blob-Generation", str(gen))
            self.end_headers()
            self.wfile.write(data)

        def do_HEAD(self):
            name = self._name()
            with lock:
                rec = store.get(name) if name else None
            if rec is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(rec["data"])))
            self.send_header("X-Blob-Generation", str(rec["gen"]))
            self.end_headers()

        def do_PUT(self):
            name = self._name()
            if not name:
                self._json({"error": "not found"}, 404)
                return
            n = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(n)
            q = urllib.parse.parse_qs(self.path.partition("?")[2])
            rotate = q.get("rotate", ["1"])[0] != "0"
            if_absent = self.headers.get("If-None-Match") == "*"
            if_match = self.headers.get("If-Match")
            with lock:
                cur = store.get(name)
                if if_absent and cur is not None:
                    self._json({"error": "exists", "generation": cur["gen"]},
                               412)
                    return
                if if_match is not None and (
                    cur is None or str(cur["gen"]) != if_match
                ):
                    self._json({"error": "generation mismatch"}, 412)
                    return
                if rotate and cur is not None:
                    store[name + ".prev"] = dict(cur)
                gen_counter[0] += 1
                store[name] = {
                    "data": data,
                    "gen": gen_counter[0],
                    "mtime": time.time(),
                }
                self._json({"generation": gen_counter[0]})

        def do_DELETE(self):
            name = self._name()
            with lock:
                deleted = store.pop(name, None) is not None if name else False
            self._json({"deleted": deleted})

    host, _, port = address.partition(":")
    httpd = ThreadingHTTPServer((host or "localhost", int(port or 0)), Handler)
    if block:
        handle = _ServerHandle(httpd, store, None)
        try:
            httpd.serve_forever()
        finally:
            httpd.server_close()
        return handle
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return _ServerHandle(httpd, store, thread)
