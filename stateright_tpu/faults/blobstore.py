"""Pluggable blob-store backend for the checkpoint plane (ROADMAP item 1:
the true multi-host residue; item 3 added the managed-store backends).

Everything durable the fleet shares — checkpoint generations, lease
records, corpus entries, member-discovery records, synced journals — is
bytes-at-a-name with one-generation history. On one machine that name is a
filesystem path and the discipline is tmp+fsync+rename (faults/ckptio.py);
across machines it is an OBJECT STORE, where the failure modes are
throttling (429/5xx), latency, partial writes, and stale listings rather
than torn renames. This module gives the repo ONE backend seam for all of
them:

- `LocalFSBlobStore` — today's on-disk layout, bit-identical: files under
  a root directory, `put` staged through a pid-unique tmp + fsync +
  `os.replace`, the previous generation rotated to ``<name>.prev``.
- `HTTPBlobStore` / `_BlobClient` — an HTTP object-store client with
  conditional-put (``If-None-Match: *``) and server-side generation
  tokens, speaking to the emulator in this module (`serve_blobd`, also
  runnable standalone as ``scripts/blobd.py``). The server rotates the
  previous payload to ``<name>.prev`` atomically on PUT — the same
  two-generation contract as the filesystem, so `ckptio.load_latest`'s
  current-then-`.prev` walk is backend-agnostic.
- `faults/blobstore_s3.py` / `faults/blobstore_gcs.py` — the MANAGED
  providers behind the same seam: pure-stdlib SigV4 / OAuth2-bearer
  signing over `faults/creds.py`'s credential chain, provider-native
  conditional writes (S3 ``If-None-Match: *`` + ETag compare, GCS
  ``x-goog-if-generation-match``), and the ``.prev`` rotation re-derived
  per provider (server-side COPY). Loaded lazily — importing this module
  never costs the managed plumbing.

Backends are chosen by ROOT URI (`backend_of`, the `knobs.BLOB_BACKENDS`
universe): a plain path or ``file://...`` is the filesystem;
``blob://host:port[/prefix]`` is the HTTP emulator store;
``s3://bucket[/prefix]`` and ``gs://bucket[/prefix]`` are the managed
providers (endpoint overrides via ``SR_TPU_S3_ENDPOINT`` /
``SR_TPU_GCS_ENDPOINT`` point them at the dialect conformance emulators
in `faults/blobdialect.py`). `faults/ckptio.py`
(`fenced_savez`/`fenced_load_latest`), `service/lease.py`, and
`store/corpus.py` all route through here when handed a blob URI, so one
shared root URI is the fleet's whole storage configuration.

**Chaos + retry discipline**: every wire op is a chaos boundary
(``blob.put`` / ``blob.get`` / ``blob.list`` / ``blob.delete`` in
faults/plan.py) and runs
under bounded retry with the supervisor's seeded deterministic backoff and
a per-op deadline. Injected 429/5xx/transport faults are retried and
counted; a server-supplied ``Retry-After``/``retry-after-ms`` hint is a
FLOOR under the deterministic backoff (the provider knows its own
throttle window; ignoring it converts one 503 into five); a ``torn`` PUT
truncates the uploaded payload (CRC-rejected at read, ``.prev`` serves —
the r13 torn-generation story over the network); a ``stale`` LIST serves
the previous listing (consumers degrade to a bigger directory, never a
wrong result); ``slow`` injects latency; an auth reject (401/403) on a
managed backend invalidates the credential chain and retries under the
same bounded budget (`creds.refresh` is its own counted chaos point).
Retry exhaustion raises `BlobUnavailable` (an OSError), which every
caller already degrades on: resume-fresh, cold corpus run, counted
publish fault. Counters are exported through the obs REGISTRY — source
"blob" for the emulator client, "blob_s3"/"blob_gcs" for the managed
clients, "creds" for the chains.

The ONE sanctioned write path into a blob store is `faults/ckptio.py`
(`fenced_savez` / `write_record`) — srlint SR002 flags a bare ``put``
anywhere else, exactly as it flags a bare `atomic_savez`: a write that
skips the seam also skips the CRC footer and the lease stamp.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import namedtuple
from typing import Optional

from ..knobs import BLOB_BACKENDS
from .plan import (
    FaultError,
    active_plan,
    deterministic_backoff,
    maybe_fault,
)

__all__ = [
    "BLOB_BACKENDS",
    "BlobStat",
    "BlobUnavailable",
    "HTTPBlobStore",
    "LocalFSBlobStore",
    "backend_of",
    "blob_backend",
    "is_blob_uri",
    "normalize_root",
    "serve_blobd",
    "split_bucket_uri",
]

#: One listing row, backend-agnostic: `name` is relative to the store's
#: root, `mtime` is the backend's last-write stamp (file mtime / server
#: PUT time) — the metadata `CorpusStore.gc`'s LRU order runs on.
BlobStat = namedtuple("BlobStat", "name size mtime")


class BlobUnavailable(OSError):
    """A blob op exhausted its bounded retry / per-op deadline. An OSError
    so every existing degrade path (resume-fresh, cold corpus, counted
    publish fault) absorbs it without new handling."""


class _Conflict(RuntimeError):
    """Server refused a conditional put (If-None-Match/If-Match miss) —
    internal; `put(if_absent=True)` surfaces it as a None return."""


#: HTTP statuses worth retrying (throttling + transient server failures).
RETRYABLE_HTTP = (429, 500, 502, 503, 504)

#: Auth rejects: retryable ONLY through the credential-chain invalidate
#: hook (`_RetryingClient._auth_retry`) — a wrong signature re-signed
#: with the same creds stays wrong, so the base client treats them as
#: terminal and the managed clients re-resolve first.
AUTH_HTTP = (401, 403)

#: Injected-latency sleep for a consumed ``slow`` fault, seconds.
SLOW_S = 0.05


def backend_of(path) -> str:
    """Which `knobs.BLOB_BACKENDS` member a root/URI selects — the ONE
    scheme dispatch (``blob://``/``s3://``/``gs://``; anything else,
    including ``file://``, is the filesystem)."""
    if isinstance(path, str):
        for backend in BLOB_BACKENDS[1:]:
            if path.startswith(backend + "://"):
                return backend
    return BLOB_BACKENDS[0]


def is_blob_uri(path) -> bool:
    """True when `path` names a WIRE store (anything but the local
    filesystem) — the predicate every consumer branches on."""
    return backend_of(path) != BLOB_BACKENDS[0]


def normalize_root(root: Optional[str]) -> Optional[str]:
    """Strip a ``file://`` scheme down to the plain path it names (so
    everything downstream sees either a filesystem path or a wire-store
    URI — the only spellings the backend seam dispatches on)."""
    if isinstance(root, str) and root.startswith("file://"):
        return root[len("file://"):] or "/"
    return root


def split_blob_uri(uri: str) -> tuple:
    """``blob://host:port/some/name`` -> ("http://host:port", "/some/name")."""
    rest = uri[len("blob://"):]
    host, slash, name = rest.partition("/")
    if not host:
        raise ValueError(f"blob URI {uri!r} has no host")
    return f"http://{host}", ("/" + name if slash else "/")


def split_bucket_uri(uri: str) -> tuple:
    """``s3://bucket/some/name`` -> ("s3", "bucket", "/some/name") — the
    managed-provider URI grammar (same name convention as
    `split_blob_uri`: absolute, leading slash)."""
    scheme, sep, rest = uri.partition("://")
    if not sep:
        raise ValueError(f"object URI {uri!r} has no scheme")
    bucket, slash, name = rest.partition("/")
    if not bucket:
        raise ValueError(f"object URI {uri!r} has no bucket")
    return scheme, bucket, ("/" + name if slash else "/")


def _retry_after_s(err) -> float:
    """The server's retry hint in seconds (0.0 = none): ``retry-after-ms``
    (the router/HTTP doors' spelling) wins over RFC ``Retry-After``."""
    headers = getattr(err, "headers", None)
    if headers is None:
        return 0.0
    ms = headers.get("retry-after-ms")
    if ms:
        try:
            return max(float(ms) / 1000.0, 0.0)
        except ValueError:
            pass
    ra = headers.get("Retry-After")
    if ra:
        try:
            return max(float(ra), 0.0)
        except ValueError:
            pass
    return 0.0


# -- the retrying wire client (shared by emulator + managed backends) ----------


class _RetryingClient:
    """The backend-agnostic half of every wire client: the chaos points,
    the bounded deterministic-backoff retry with the server's Retry-After
    hint as a floor, the torn/stale/slow special handling, the
    auth-invalidate hook, and the counter set — subclasses implement only
    the five raw `_do_*` verbs (one provider round trip each, raising
    `urllib.error.HTTPError` for status failures). Cached per store
    identity (`_cached_client`) so every URI op against one server shares
    one counter set and one stale-list cache."""

    retry_limit = 4
    op_deadline_s = 30.0
    backoff_base_s = 0.02
    backoff_cap_s = 0.5

    #: obs REGISTRY source the counters export under.
    metrics_source = "blob"

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")
        self._lock = threading.Lock()
        self._stale_cache: dict = {}  # prefix -> previous listing
        self.counters = {
            "ops": 0,
            "retries": 0,
            "backoff_ms": 0,
            "faults": 0,
            "torn_puts": 0,
            "stale_lists": 0,
            "slow_ops": 0,
            "unavailable": 0,
            "retry_after_waits": 0,
            "auth_retries": 0,
        }
        from ..obs import REGISTRY

        self._metrics_name = REGISTRY.register(
            self.metrics_source, self.metrics
        )

    def metrics(self) -> dict:
        with self._lock:
            return dict(self.counters)

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    # -- retry/chaos wrapper ---------------------------------------------------

    def _auth_retry(self, err) -> bool:
        """Hook for a 401/403: return True to treat the reject as
        retryable (after invalidating whatever credential produced it).
        The base client has no credentials, so a reject is terminal."""
        return False

    def _op(
        self,
        point: str,
        fn,
        chaos: bool = True,
        deadline_s: Optional[float] = None,
        **ctx,
    ):
        """Run one server round trip under the chaos point + bounded
        deterministic-backoff retry + per-op deadline. 404s and
        conditional-put conflicts pass straight through (they are answers,
        not failures); everything transport-shaped is retried until the
        budget runs out, then surfaced as `BlobUnavailable`. A throttle
        response carrying ``Retry-After``/``retry-after-ms`` floors the
        next backoff (counted ``retry_after_waits``); a 401/403 retries
        only when `_auth_retry` invalidated a credential chain (counted
        ``auth_retries``).

        `chaos=False` skips the injection point (real transport failures
        are still retried): reserved for ops the chaos plane itself can
        re-enter — the flight-recorder journal's blob mirror, where an
        injected fault would be recorded as a `fault.injected` event into
        the very journal whose sync is mid-flight (journal `_io_lock` and
        plan lock re-entered: a self-deadlock, found by the smoke's blob
        partition phase)."""
        self._count("ops")
        plan = active_plan() if chaos else None
        if plan is not None and plan.consume_special(point, "slow"):
            self._count("slow_ops")
            time.sleep(SLOW_S)
        seed = plan.seed if plan is not None else 0
        deadline = time.monotonic() + (
            deadline_s if deadline_s is not None else self.op_deadline_s
        )
        attempt = 0
        last: Optional[BaseException] = None
        floor_s = 0.0
        while True:
            try:
                if chaos:
                    maybe_fault(point, store=self.base_url, **ctx)
                return fn()
            except (FileNotFoundError, _Conflict):
                raise
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    raise FileNotFoundError(
                        f"{self.base_url}: no such blob ({ctx})"
                    ) from e
                if e.code == 412:
                    raise _Conflict(str(e)) from e
                if e.code in AUTH_HTTP:
                    if not self._auth_retry(e):
                        raise BlobUnavailable(
                            f"blob op {point} rejected with HTTP {e.code}"
                        ) from e
                    self._count("auth_retries")
                elif e.code not in RETRYABLE_HTTP:
                    raise BlobUnavailable(
                        f"blob op {point} failed with HTTP {e.code}"
                    ) from e
                else:
                    floor_s = _retry_after_s(e)
                last = e
            except (
                FaultError,
                urllib.error.URLError,
                ConnectionError,
                TimeoutError,
                http.client.HTTPException,
                OSError,
            ) as e:
                last = e
            self._count("faults")
            attempt += 1
            if attempt > self.retry_limit or time.monotonic() >= deadline:
                self._count("unavailable")
                raise BlobUnavailable(
                    f"blob op {point} against {self.base_url} exhausted "
                    f"{attempt} attempts (last: {type(last).__name__}: "
                    f"{last})"
                ) from last
            delay = deterministic_backoff(
                seed, f"{point}.backoff", attempt - 1,
                self.backoff_base_s, self.backoff_cap_s,
            )
            if floor_s > delay:
                self._count("retry_after_waits")
                delay = floor_s
            floor_s = 0.0
            delay = min(delay, max(deadline - time.monotonic(), 0.0))
            self._count("retries")
            self._count("backoff_ms", int(delay * 1000))
            time.sleep(delay)

    # -- raw verbs (one round trip; subclasses implement) ----------------------

    def _do_put(
        self, name: str, data: bytes, rotate: bool, if_absent: bool
    ) -> int:
        raise NotImplementedError

    def _do_get(self, name: str) -> bytes:
        raise NotImplementedError

    def _do_delete(self, name: str) -> bool:
        raise NotImplementedError

    def _do_list(self, prefix: str) -> list:
        raise NotImplementedError

    def _do_exists(self, name: str) -> bool:
        raise NotImplementedError

    # -- the chaos-wrapped verb surface ----------------------------------------

    def put(
        self,
        name: str,
        data: bytes,
        rotate: bool = True,
        if_absent: bool = False,
        chaos: bool = True,
        deadline_s: Optional[float] = None,
    ) -> Optional[int]:
        """Upload one blob; the backend rotates the previous payload to
        ``<name>.prev`` when `rotate` (the two-generation contract).
        `if_absent=True` is the conditional put (``If-None-Match: *`` /
        ``ifGenerationMatch=0``): None means another writer got there
        first — the content-addressed idempotence the corpus publish
        rides. A consumed ``torn`` fault truncates the payload BEFORE
        upload: the partial PUT the read-side CRC must reject.
        `chaos=False` (journal mirror only) skips the injection point —
        see `_op`; `deadline_s` overrides the per-op deadline
        (best-effort callers cap their stall).

        Returns the backend's generation token — NEGATED when the upload
        was torn, so the caller KNOWS this write is not trustworthy
        (ckptio must not mark the path written-intact, or a later write
        would rotate the torn generation over the good `.prev`, and a
        conditional republish would 412-skip the repair forever)."""
        plan = active_plan() if chaos else None
        torn = False
        if plan is not None and plan.consume_special("blob.put", "torn"):
            self._count("torn_puts")
            data = data[: max(len(data) // 2, 1)]
            torn = True
        try:
            gen = self._op(
                "blob.put",
                lambda: self._do_put(name, data, rotate, if_absent),
                chaos=chaos, deadline_s=deadline_s, name=name[-64:],
            )
        except _Conflict:
            return None
        return -gen if torn and gen else gen

    def get(self, name: str) -> bytes:
        """One blob's bytes; FileNotFoundError when absent (an answer, not
        a failure — never retried)."""
        return self._op(
            "blob.get", lambda: self._do_get(name), name=name[-64:]
        )

    def delete(self, name: str) -> bool:
        # Its own chaos point: deletes riding ``blob.put`` would shift
        # the put hit counter (replayed torn-put plans landing on the
        # wrong upload) and let put-targeted rules fire on GC traffic.
        return self._op(
            "blob.delete", lambda: self._do_delete(name), name=name[-64:]
        )

    def list(self, prefix: str = "/") -> list:
        """Every blob under `prefix` as `BlobStat` rows (absolute names).
        A consumed ``stale`` fault serves the PREVIOUS listing for this
        prefix — the eventually-consistent LIST every consumer must
        tolerate (GC sweeps a smaller set, discovery sees yesterday's
        members; both degrade, neither is wrong)."""
        plan = active_plan()
        if plan is not None and plan.consume_special("blob.list", "stale"):
            self._count("stale_lists")
            return list(self._stale_cache.get(prefix, ()))
        out = self._op(
            "blob.list", lambda: self._do_list(prefix), prefix=prefix[-64:]
        )
        self._stale_cache[prefix] = list(out)
        return out

    def exists(self, name: str) -> bool:
        """Existence probe — answers without downloading the payload
        (checkpoint generations are multi-MB; `any_generation` probes two
        names per corpus lookup). Runs with `chaos=False`: letting probes
        consume ``blob.get`` hits would shift the point's hit numbering
        and break replayed plans (the same reason deletes got their own
        point), and the payload GET that always follows a positive probe
        is the real chaos surface anyway."""
        try:
            return bool(
                self._op(
                    "blob.get", lambda: self._do_exists(name),
                    chaos=False, name=name[-64:],
                )
            )
        except (FileNotFoundError, BlobUnavailable):
            return False


class _BlobClient(_RetryingClient):
    """The ``blob://`` emulator dialect: plain HTTP against `serve_blobd`
    (``/b/<name>`` + ``/list``), server-side generation tokens, no auth."""

    def _url(self, name: str) -> str:
        return self.base_url + "/b" + urllib.parse.quote(name)

    def _request(self, req, timeout: float = 10.0):
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()

    def _do_put(
        self, name: str, data: bytes, rotate: bool, if_absent: bool
    ) -> int:
        headers = {"Content-Type": "application/octet-stream"}
        if if_absent:
            headers["If-None-Match"] = "*"
        req = urllib.request.Request(
            self._url(name) + f"?rotate={int(bool(rotate))}",
            data=data,
            method="PUT",
            headers=headers,
        )
        out = json.loads(self._request(req) or b"{}")
        return int(out.get("generation", 0))

    def _do_get(self, name: str) -> bytes:
        return self._request(urllib.request.Request(self._url(name)))

    def _do_delete(self, name: str) -> bool:
        req = urllib.request.Request(self._url(name), method="DELETE")
        out = json.loads(self._request(req) or b"{}")
        return bool(out.get("deleted"))

    def _do_list(self, prefix: str) -> list:
        req = urllib.request.Request(
            self.base_url + "/list?prefix=" + urllib.parse.quote(prefix)
        )
        out = json.loads(self._request(req) or b"{}")
        return [
            BlobStat(b["name"], int(b["size"]), float(b["mtime"]))
            for b in out.get("blobs", ())
        ]

    def _do_exists(self, name: str) -> bool:
        self._request(urllib.request.Request(self._url(name), method="HEAD"))
        return True


_clients: dict = {}
_clients_lock = threading.Lock()


def _cached_client(key, factory):
    """One client per store identity (server URL / (provider, endpoint,
    bucket)) so counters, stale caches, and credential chains are
    shared across every URI op against that store."""
    with _clients_lock:
        c = _clients.get(key)
        if c is None:
            c = _clients[key] = factory()
        return c


def _client(base_url: str) -> _BlobClient:
    return _cached_client(base_url, lambda: _BlobClient(base_url))


# -- URI-level helpers (what ckptio routes through) ----------------------------


def uri_client(uri: str) -> tuple:
    """(client, absolute name) for one wire-store URI — the scheme
    dispatch behind `get_blob`/`put_blob`/`delete_blob`/`blob_exists`.
    Managed clients import lazily: a fleet on ``blob://`` never pays for
    the signing plumbing."""
    backend = backend_of(uri)
    if backend == "blob":
        base, name = split_blob_uri(uri)
        return _client(base), name
    if backend == "s3":
        from .blobstore_s3 import s3_client

        _scheme, bucket, name = split_bucket_uri(uri)
        return s3_client(bucket), name
    if backend == "gs":
        from .blobstore_gcs import gcs_client

        _scheme, bucket, name = split_bucket_uri(uri)
        return gcs_client(bucket), name
    raise ValueError(f"not a wire-store URI: {uri!r}")


def get_blob(uri: str) -> bytes:
    c, name = uri_client(uri)
    return c.get(name)


def put_blob(
    uri: str,
    data: bytes,
    rotate: bool = True,
    if_absent: bool = False,
    chaos: bool = True,
    deadline_s: Optional[float] = None,
) -> Optional[int]:
    c, name = uri_client(uri)
    return c.put(
        name, data, rotate=rotate, if_absent=if_absent, chaos=chaos,
        deadline_s=deadline_s,
    )


def delete_blob(uri: str) -> bool:
    c, name = uri_client(uri)
    return c.delete(name)


def blob_exists(uri: str) -> bool:
    c, name = uri_client(uri)
    return c.exists(name)


# -- rooted store views (the corpus-GC / discovery listing seam) ---------------


#: LocalFS previous-listing cache for the ``stale`` LIST fault, keyed
#: (abs root, prefix) — module-level so every rooted view over one
#: directory shares it, mirroring the wire clients' per-server cache.
#: This is what lets the stale-degrade invariance tests run the SAME
#: chaos plan on ``file://`` as on the three wire backends.
_local_stale: dict = {}


class LocalFSBlobStore:
    """The filesystem backend behind the same four-verb surface: files
    under `root`, put through the pid-unique tmp + fsync + `os.replace`
    discipline with ``.prev`` rotation — byte-identical to what
    `ckptio.atomic_savez` leaves on disk, which is why routing `gc`/
    listing consumers through this view changes nothing on local roots."""

    def __init__(self, root: str):
        self.root = root

    def _path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def list(self, prefix: str = "") -> list:
        plan = active_plan()
        key = (os.path.abspath(self.root or "."), prefix)
        if plan is not None and plan.consume_special("blob.list", "stale"):
            return list(_local_stale.get(key, ()))
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for n in sorted(names):
            if prefix and not n.startswith(prefix):
                continue
            try:
                st = os.stat(self._path(n))
            except OSError:
                continue
            if not os.path.isfile(self._path(n)):
                continue
            out.append(BlobStat(n, int(st.st_size), float(st.st_mtime)))
        _local_stale[key] = list(out)
        return out

    def get(self, name: str) -> bytes:
        with open(self._path(name), "rb") as f:
            return f.read()

    def put(
        self,
        name: str,
        data: bytes,
        rotate: bool = True,
        if_absent: bool = False,
    ) -> Optional[int]:
        path = self._path(name)
        if if_absent and os.path.exists(path):
            return None
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:  # srlint: ckpt-ok the LocalFS blob backend IS the sanctioned tmp/fsync/rename writer (rotation below)
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if rotate and os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
        # Make the renames themselves durable (best-effort: not every
        # filesystem supports directory fsync).
        try:
            dfd = os.open(self.root or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        return 1

    def delete(self, name: str) -> bool:
        try:
            os.unlink(self._path(name))
            return True
        except OSError:
            return False

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))


class RootedWireStore:
    """A rooted view over one wire client: names are relative to the root
    URI's prefix, so `CorpusStore.gc` / discovery listings run the same
    code on every backend. Subclasses (`HTTPBlobStore`, the managed
    stores) only choose the client and parse the prefix."""

    def __init__(self, root_uri: str, client, prefix: str):
        if not prefix.endswith("/"):
            prefix += "/"
        self.root = root_uri
        self._c = client
        self._prefix = prefix

    def list(self, prefix: str = "") -> list:
        out = self._c.list(self._prefix + prefix)
        cut = len(self._prefix)
        return [BlobStat(b.name[cut:], b.size, b.mtime) for b in out]

    def get(self, name: str) -> bytes:
        return self._c.get(self._prefix + name)

    def put(
        self,
        name: str,
        data: bytes,
        rotate: bool = True,
        if_absent: bool = False,
    ) -> Optional[int]:
        return self._c.put(
            self._prefix + name, data, rotate=rotate, if_absent=if_absent
        )

    def delete(self, name: str) -> bool:
        return self._c.delete(self._prefix + name)

    def exists(self, name: str) -> bool:
        return self._c.exists(self._prefix + name)


class HTTPBlobStore(RootedWireStore):
    """The ``blob://`` emulator store, rooted at the URI's prefix."""

    def __init__(self, root_uri: str):
        base, prefix = split_blob_uri(root_uri)
        super().__init__(root_uri, _client(base), prefix)


def blob_backend(root: str):
    """The rooted store view for one root URI — `HTTPBlobStore` for
    ``blob://``, the managed stores for ``s3://``/``gs://`` (lazy
    import), `LocalFSBlobStore` for a plain/‌``file://`` path. The ONE
    dispatch every backend-agnostic consumer (corpus GC, member
    discovery, journal-root listing) goes through."""
    root = normalize_root(root)
    backend = backend_of(root)
    if backend == "blob":
        return HTTPBlobStore(root)
    if backend == "s3":
        from .blobstore_s3 import S3BlobStore

        return S3BlobStore(root)
    if backend == "gs":
        from .blobstore_gcs import GCSBlobStore

        return GCSBlobStore(root)
    return LocalFSBlobStore(root)


# -- the emulator server -------------------------------------------------------


class _ServerHandle:
    """serve_blobd's return: the bound address, the live store dict (tests
    reach in to corrupt/inspect payloads), the env vars a client process
    needs to reach this server (empty for the native dialect; endpoint +
    static credentials for the provider dialects), and shutdown."""

    dialect = "blob"

    def __init__(self, httpd, store, thread):
        self.httpd = httpd
        self.store = store
        self.thread = thread
        self.env: dict = {}

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"{host}:{port}"

    @property
    def root_uri(self) -> str:
        return f"blob://{self.address}"

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.thread is not None:
            self.thread.join(timeout=5.0)


def serve_blobd(
    address: str = "localhost:0", block: bool = False, dialect: str = "blob"
):
    """The in-proc HTTP object-store emulator (`scripts/blobd.py` runs it
    standalone). `dialect` selects the wire protocol: the native
    ``blob`` protocol below, or the provider-conformance dialects
    (``s3``/``gcs`` — SigV4/OAuth verification, provider error shapes,
    metadata + token planes) served by `faults/blobdialect.py`.

    Native protocol — deliberately the S3/GCS-shaped minimum:

    - ``PUT /b/<name>?rotate=0|1`` — store bytes; ``rotate=1`` moves the
      previous payload to ``<name>.prev`` atomically first (the
      two-generation contract). ``If-None-Match: *`` is the conditional
      put (412 when the name exists); ``If-Match: <gen>`` compares
      against the server's generation token. Returns ``{"generation": g}``.
    - ``GET /b/<name>`` — the bytes (+ ``X-Blob-Generation``); 404 absent.
    - ``DELETE /b/<name>`` — ``{"deleted": bool}``.
    - ``GET /list?prefix=`` — ``{"blobs": [{name,size,mtime,generation}]}``.
    - ``GET /healthz`` — liveness.

    Storage is in-memory (an emulator, not a database): one dict guarded
    by a lock, rotation + conditional checks atomic under it.
    """
    if dialect != "blob":
        from .blobdialect import serve_dialect

        return serve_dialect(dialect, address=address, block=block)
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    store: dict = {}  # name -> {"data": bytes, "gen": int, "mtime": float}
    lock = threading.Lock()
    gen_counter = [0]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _name(self) -> Optional[str]:
            path = urllib.parse.unquote(self.path.partition("?")[0])
            if not path.startswith("/b/"):
                return None
            return path[len("/b"):]

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                with lock:
                    self._json({"ok": 1, "blobs": len(store)})
                return
            if path == "/list":
                q = urllib.parse.parse_qs(query)
                prefix = urllib.parse.unquote(q.get("prefix", [""])[0])
                with lock:
                    blobs = [
                        {
                            "name": n,
                            "size": len(rec["data"]),
                            "mtime": rec["mtime"],
                            "generation": rec["gen"],
                        }
                        for n, rec in sorted(store.items())
                        if n.startswith(prefix)
                    ]
                self._json({"blobs": blobs})
                return
            name = self._name()
            with lock:
                rec = store.get(name) if name else None
                data = rec["data"] if rec else None
                gen = rec["gen"] if rec else 0
            if data is None:
                self._json({"error": "no such blob"}, 404)
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-Blob-Generation", str(gen))
            self.end_headers()
            self.wfile.write(data)

        def do_HEAD(self):
            name = self._name()
            with lock:
                rec = store.get(name) if name else None
            if rec is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(rec["data"])))
            self.send_header("X-Blob-Generation", str(rec["gen"]))
            self.end_headers()

        def do_PUT(self):
            name = self._name()
            if not name:
                self._json({"error": "not found"}, 404)
                return
            n = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(n)
            q = urllib.parse.parse_qs(self.path.partition("?")[2])
            rotate = q.get("rotate", ["1"])[0] != "0"
            if_absent = self.headers.get("If-None-Match") == "*"
            if_match = self.headers.get("If-Match")
            with lock:
                cur = store.get(name)
                if if_absent and cur is not None:
                    self._json({"error": "exists", "generation": cur["gen"]},
                               412)
                    return
                if if_match is not None and (
                    cur is None or str(cur["gen"]) != if_match
                ):
                    self._json({"error": "generation mismatch"}, 412)
                    return
                if rotate and cur is not None:
                    store[name + ".prev"] = dict(cur)
                gen_counter[0] += 1
                store[name] = {
                    "data": data,
                    "gen": gen_counter[0],
                    "mtime": time.time(),
                }
                self._json({"generation": gen_counter[0]})

        def do_DELETE(self):
            name = self._name()
            with lock:
                deleted = store.pop(name, None) is not None if name else False
            self._json({"deleted": deleted})

    host, _, port = address.partition(":")
    httpd = ThreadingHTTPServer((host or "localhost", int(port or 0)), Handler)
    if block:
        handle = _ServerHandle(httpd, store, None)
        try:
            httpd.serve_forever()
        finally:
            httpd.server_close()
        return handle
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return _ServerHandle(httpd, store, thread)
