"""Chaos plane: seeded, deterministic fault injection at named boundaries.

The reference crate survives faults by construction (panic-on-invariant,
lossy/duplicating network *models*); this port accumulated real recovery
machinery — overflow abort+regrow, tiered-store service exits,
checkpoint/resume, service preempt/resume — and this module is what
exercises it ON PURPOSE, Jepsen-style: a `FaultPlan` names the faults, the
engines call `maybe_fault(point)` at every failure boundary they already
have, and the supervisor (faults/supervisor.py) proves recovery converges
to bit-identical results.

Injection points (the name is the contract; grep for `maybe_fault(`):

- ``engine.step``     — engine step dispatch (frontier per-batch, resident/
                        sharded per-chunk, simulation per-round — ctx
                        ``engine="simulation", round=r``), BEFORE the
                        device call
- ``engine.chunk``    — between resident/sharded chunk dispatches
                        (preemption mid-run; the carry is sound here)
- ``store.spill``     — tiered-store high-water eviction entry
- ``store.resolve``   — tiered-store suspect resolution
- ``store.append``    — host spill-tier append (I/O boundary)
- ``store.service``   — resident tiered-store host service entry (queue
                        compaction + suspect injection + eviction; the
                        suspended carry is sound, nothing mutated yet)
- ``shard.transfer``  — sharded engine per-shard service transfer
                        (ctx ``shard=i``)
- ``table.insert_retry`` — Pallas hash table spilled-lane re-offer
                        (tensor/pallas_hashtable.py host handle; ctx
                        ``pending=n, round=r``) — the re-offer happens
                        before any further table mutation, so a fault here
                        is exactly retriable by re-running the insert
- ``ckpt.write``      — checkpoint write; the ``torn`` kind CORRUPTS the
                        just-written file instead of raising
- ``service.step``    — check-service fused step (ctx ``jobs=[ids]``)
- ``service.http``    — service/fleet HTTP front end (converted to a 503
                        with a ``Retry-After`` header)
- ``checker.run``     — TpuChecker search-thread entry
- ``fleet.replica_crash`` — fleet replica driver entry (ctx ``replica=i``);
                        the ``crash`` kind kills that replica for good —
                        the router requeues its jobs from checkpoints
- ``fleet.replica_hang``  — fleet replica health probe (ctx ``replica=i``);
                        a ``hang`` here parks the probe until the router's
                        probe deadline expires (suspect accounting)
- ``router.timeout``  — fleet router submit path to one replica (ctx
                        ``replica=i``), BEFORE the replica is touched —
                        retried with deterministic backoff on a survivor
- ``fleet.steal``     — cross-replica work-steal boundary (ctx ``src=i,
                        dst=j``), BEFORE the queued job is withdrawn, so a
                        fault here leaves the job exactly where it was
- ``corpus.load``     — warm-start corpus lookup (store/corpus.py; ctx
                        ``key=<prefix>``), BEFORE the entry file is read —
                        a fault degrades the submission to a COLD run
                        (correct, just slower), never to wrong results
- ``corpus.publish``  — warm-start corpus publish (ctx ``key=<prefix>,
                        states=n``), BEFORE the atomic write — a fault
                        leaves no partial entry and the publishing job's
                        own result is unaffected
- ``corpus.gc``       — corpus eviction sweep entry (store/corpus.py
                        ``CorpusStore.gc``, ctx ``max_bytes=n``), BEFORE
                        any file is removed — a fault aborts the sweep
                        with the directory intact (bigger, never wrong)
- ``fleet.partition`` — router↔replica connectivity (ctx ``replica=i``):
                        fires in the router's probe path (in-proc
                        Replica.probe) and in EVERY RemoteReplica HTTP
                        request, so an injected partition makes one
                        replica unreachable from the router while the
                        replica itself keeps running — the false-positive
                        death the lease fence exists for
- ``fleet.zombie_write`` — the ``bypass`` kind is CONSUMED by
                        `ckptio.fenced_savez` (via `consume_bypass`): the
                        write skips its pre-write lease check, simulating
                        a hung-but-alive replica whose write passed the
                        check before revocation and landed after (the
                        open-fd race) — the stale generation the
                        read-side fence must reject
- ``lease.revoke_race`` — lease revocation entry (service/lease.py
                        LeaseStore.revoke, ctx ``member=<name>``), BEFORE
                        the revocation is persisted — a fault here leaves
                        the lease granted and the router's death handling
                        must re-run the revocation on its next tick
                        (revoke-before-requeue stays atomic per member)
- ``blob.put``        — object-store write (faults/blobstore.py HTTP
                        backend, ctx ``name=<key>``): raising kinds
                        (``http``/``io`` — injected 429/5xx/transport
                        failures) are absorbed by the client's bounded
                        deterministic-backoff retry; the ``torn`` kind is
                        CONSUMED (`consume_special`) and truncates the
                        uploaded payload — a partial PUT the read-side CRC
                        footer must reject (`.prev` serves, exactly like
                        the r13 torn generation); the ``slow`` kind is
                        consumed as injected latency
- ``blob.get``        — object-store read: raising kinds retried under
                        the per-op deadline; exhaustion degrades to the
                        caller's missing/corrupt path (resume-fresh, cold
                        corpus run — counted, never wrong)
- ``blob.list``       — object-store listing (corpus GC, journal-root
                        discovery): raising kinds retried; the ``stale``
                        kind is consumed and serves the PREVIOUS listing
                        (an eventually-consistent store's stale LIST) —
                        consumers must degrade to a bigger directory /
                        shorter merge, never a wrong result
- ``blob.delete``     — object-store deletion (GC sweeps, record
                        retirement): raising kinds retried; exhaustion
                        degrades to a skipped eviction (bigger directory,
                        never a wrong one) — its own point so delete
                        traffic never shifts ``blob.put`` hit numbering
                        in a replayed plan
- ``creds.refresh``   — managed-store credential resolve/refresh
                        (faults/creds.py CredentialChain, ctx
                        ``provider=s3|gcs``): an injected fault fails ONE
                        chain resolve — near expiry the stale credentials
                        keep serving through the grace window (counted
                        ``grace_served``), past it the chain raises
                        `CredentialError` (an OSError) and the blob
                        client's bounded retry absorbs it like any
                        transport failure: an expiring token
                        mid-checkpoint degrades to bounded retry, never a
                        lost generation
- ``fleet.rejoin``    — replica rejoin entry (service/fleet.py
                        ServiceFleet.rejoin_replica, ctx ``replica=i``),
                        BEFORE the fresh lease grant and the respawn — an
                        injected fault aborts the rejoin with nothing
                        changed (not even a burned epoch; the member
                        stays dead and the caller simply retries), and
                        the rejoin-vs-stale-zombie race it covers is
                        fence-rejected: the restarted member holds a
                        FRESH epoch, so the old incarnation's writes
                        fail the exact-epoch check
- ``fleet.autoscale`` — autoscaler actuation entry (service/autoscale.py
                        reconcile tick and ServiceFleet.scale_out /
                        scale_in, ctx ``action="tick"|"scale_out"|
                        "scale_in"``), BEFORE any signal is acted on, any
                        lease granted, or any member touched — an
                        injected fault aborts that reconcile tick with
                        the fleet EXACTLY as it was (no spawned process,
                        no burned epoch, no drained member); the
                        autoscaler counts it (``aborted_ticks``) and the
                        next tick re-reads the signals and re-decides

Determinism: every decision is a pure function of (plan seed, per-point hit
counter, rule spec) — no RNG state, no wall clock — so a failing chaos run
replays exactly from its `SR_TPU_FAULTS=` string.

Faults raise typed exceptions rooted at `FaultError`; the ``hang`` kind
blocks on the plan's cancel gate instead (the watchdog converts it into a
retriable `WatchdogTimeout`), and ``torn`` is consumed by the checkpoint
writer via `consume_corruption`.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Optional


# -- fault taxonomy ------------------------------------------------------------


class FaultError(RuntimeError):
    """Base of every injected fault (so recovery code can tell an injected
    fault from an organic one when classifying retriability)."""


class DeviceOOM(FaultError):
    """Simulated device allocator exhaustion (the XLA RESOURCE_EXHAUSTED
    shape) at a step dispatch."""


class XlaError(FaultError):
    """Simulated generic XlaRuntimeError at a step dispatch."""


class PreemptionFault(FaultError):
    """Simulated TPU preemption between chunk dispatches."""


class SpillIOError(FaultError, OSError):
    """Simulated host spill-tier I/O failure."""


class ShardFault(FaultError):
    """Simulated single-shard failure during a per-shard transfer."""


class PoisonFault(FaultError):
    """Simulated poison job: its step raises every time it runs."""


class HttpFault(FaultError):
    """Simulated service HTTP front-end failure (rendered as a 503)."""


class ReplicaCrash(FaultError):
    """Simulated fleet replica death: the replica's driver stops for good
    and the router must recover its jobs from the checkpoint plane."""


class WatchdogTimeout(FaultError):
    """A hang converted into a retriable fault (by the supervisor watchdog
    cancelling the hang gate, or the gate's own self-limit)."""


#: kind string -> exception class for the raising kinds. ``hang`` and
#: ``torn`` are handled specially (gate / write-corruption).
KINDS = {
    "oom": DeviceOOM,
    "xla": XlaError,
    "preempt": PreemptionFault,
    "io": SpillIOError,
    "shard": ShardFault,
    "poison": PoisonFault,
    "http": HttpFault,
    "crash": ReplicaCrash,
}

#: Kinds consumed by the boundary itself instead of raised: ``hang`` parks
#: on the cancel gate, ``torn`` corrupts a just-written payload, ``bypass``
#: skips a guard, ``stale`` serves a previous listing, ``slow`` injects
#: latency (see `consume_special`).
_SPECIAL_KINDS = ("hang", "torn", "bypass", "stale", "slow")


def _u01(seed: int, point: str, hit: int) -> float:
    """Deterministic uniform in [0, 1): crc32 of (seed, point, hit)."""
    h = zlib.crc32(f"{seed}:{point}:{hit}".encode()) & 0xFFFFFFFF
    return h / 2**32


def deterministic_backoff(
    seed: int,
    point: str,
    attempt: int,
    base_s: float,
    cap_s: float,
    factor: float = 2.0,
) -> float:
    """THE one spelling of the repo's seeded exponential backoff delay
    (supervisor retry slices, router submit retries, blob-store op
    retries): `min(base * factor^attempt, cap)` scaled by a deterministic
    jitter in [0.5, 1.5) derived from `(seed, point, attempt)` — replayable
    run to run, never synchronized across differently-seeded actors."""
    if base_s <= 0:
        return 0.0
    delay = min(base_s * factor ** attempt, cap_s)
    return delay * (0.5 + _u01(seed, point, attempt))


@dataclass
class FaultRule:
    """One injection rule. Fires on hits of `point` numbered in
    (`after`, `after` + `times`] (1-based per-point hit counter; `times=-1`
    means every hit past `after`), optionally thinned by `prob` (decided by
    the deterministic per-hit hash) and filtered by `match` context equality
    (e.g. ``{"job": 3}`` fires only when the point reports that job in its
    batch)."""

    point: str
    kind: str
    after: int = 0
    times: int = 1
    prob: Optional[float] = None
    match: dict = field(default_factory=dict)
    fired: int = 0  # mutable: how many times this rule has fired

    def __post_init__(self):
        if self.kind not in KINDS and self.kind not in _SPECIAL_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} "
                f"(known: {sorted(KINDS) + list(_SPECIAL_KINDS)})"
            )

    def wants(self, seed: int, hit: int, ctx: dict) -> bool:
        if hit <= self.after:
            return False
        if self.times >= 0 and self.fired >= self.times:
            return False
        for k, v in self.match.items():
            got = ctx.get(k)
            if isinstance(got, (list, tuple, set)):
                if v not in got:
                    return False
            elif got != v:
                return False
        if self.prob is not None and _u01(seed, self.point, hit) >= self.prob:
            return False
        return True


class FaultPlan:
    """A seeded set of `FaultRule`s plus the runtime machinery the rules
    need: per-point hit counters, injected-fault accounting, and the hang
    cancel gate. Thread-safe (the service scheduler and supervisor worker
    threads hit the same plan)."""

    def __init__(
        self,
        rules: Optional[list] = None,
        seed: int = 0,
        hang_limit_s: float = 30.0,
    ):
        self.seed = seed
        self.rules: list[FaultRule] = list(rules or [])
        self.hang_limit_s = hang_limit_s
        self.injected: dict[str, int] = {}  # "point:kind" -> count
        self.hits: dict[str, int] = {}
        self._lock = threading.Lock()
        # Hang-gate wakeup: a generation counter under a Condition, NOT a
        # shared auto-clear Event — a cancel with nobody parked must not
        # short-circuit the NEXT gate, and one cancel must release EVERY
        # currently-parked gate.
        self._cancel_cond = threading.Condition()
        self._cancel_gen = 0
        self.tracer = None  # optional obs.Tracer, set by the supervisor
        # Optional obs.EventJournal: adopted by the flight recorder (the
        # fleet router / check service set it when they journal) so every
        # injection lands in the run's journal as a `fault.injected`
        # event — chaos runs become auditable recordings.
        self.events = None

    # -- construction ----------------------------------------------------------

    def rule(self, point: str, kind: str, **kw) -> "FaultPlan":
        """Fluent rule append: `plan.rule("engine.step", "oom", times=2)`."""
        self.rules.append(FaultRule(point, kind, **kw))
        return self

    @classmethod
    def from_env(cls, env: Optional[str] = None) -> Optional["FaultPlan"]:
        """Parse the ``SR_TPU_FAULTS`` grammar; None when unset/empty.

        Semicolon-separated clauses; ``seed=N`` and ``hang_limit_s=X`` set
        plan knobs, anything else is ``point:kind[:key=val]*`` with rule
        keys after/times/prob plus arbitrary match filters, e.g.::

            SR_TPU_FAULTS="seed=7;engine.step:oom:times=2;store.spill:io;\
service.step:poison:job=3:times=-1"
        """
        if env is None:
            env = os.environ.get("SR_TPU_FAULTS", "")
        env = env.strip()
        if not env:
            return None
        plan = cls()
        for clause in env.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if ":" not in clause:
                k, _, v = clause.partition("=")
                if k == "seed":
                    plan.seed = int(v)
                elif k == "hang_limit_s":
                    plan.hang_limit_s = float(v)
                else:
                    raise ValueError(
                        f"bad SR_TPU_FAULTS clause {clause!r} (expected "
                        "seed=N, hang_limit_s=X, or point:kind[:k=v]*)"
                    )
                continue
            parts = clause.split(":")
            point, kind, opts = parts[0], parts[1], parts[2:]
            kw: dict = {}
            match: dict = {}
            for opt in opts:
                k, _, v = opt.partition("=")
                if k in ("after", "times"):
                    kw[k] = int(v)
                elif k == "prob":
                    kw["prob"] = float(v)
                else:
                    # Context match filter; ints when they look like ints.
                    try:
                        match[k] = int(v)
                    except ValueError:
                        match[k] = v
            plan.rules.append(FaultRule(point, kind, match=match, **kw))
        return plan

    def spec(self) -> str:
        """The plan re-serialized in the `from_env` grammar (replay
        currency for logs and smoke-script output)."""
        out = [f"seed={self.seed}"]
        for r in self.rules:
            parts = [r.point, r.kind]
            if r.after:
                parts.append(f"after={r.after}")
            if r.times != 1:
                parts.append(f"times={r.times}")
            if r.prob is not None:
                parts.append(f"prob={r.prob}")
            parts.extend(f"{k}={v}" for k, v in r.match.items())
            out.append(":".join(parts))
        return ";".join(out)

    # -- runtime ---------------------------------------------------------------

    def _record(self, point: str, kind: str) -> None:
        key = f"{point}:{kind}"
        self.injected[key] = self.injected.get(key, 0) + 1
        if self.tracer is not None:
            self.tracer.instant(
                "fault_injected", cat="faults", point=point, kind=kind
            )
        if self.events is not None:
            try:
                self.events.emit("fault.injected", point=point, kind=kind)
            except Exception:  # noqa: BLE001 — recording never blocks a fault
                pass

    def fire(self, point: str, ctx: dict) -> None:
        """Account one hit of `point`; raise the matching fault (if any).
        ``torn`` rules never fire here — the checkpoint writer pulls them
        via `consume_corruption` so the write itself can be corrupted."""
        with self._lock:
            hit = self.hits.get(point, 0) + 1
            self.hits[point] = hit
            rule = next(
                (
                    r
                    for r in self.rules
                    if r.point == point
                    and (r.kind in KINDS or r.kind == "hang")
                    and r.wants(self.seed, hit, ctx)
                ),
                None,
            )
            if rule is None:
                return
            rule.fired += 1
            self._record(point, rule.kind)
        if rule.kind == "hang":
            self._hang(point)
            return
        exc = KINDS[rule.kind]
        detail = {k: v for k, v in ctx.items() if isinstance(v, (int, str))}
        raise exc(
            f"injected {rule.kind} fault at {point} (hit {hit}"
            + (f", {detail}" if detail else "")
            + ")"
        )

    def consume_special(self, point: str, kind: str) -> bool:
        """True iff a rule of consumed `kind` fires for this hit — the
        caller then acts the fault out itself instead of raising: ``torn``
        corrupts a just-written payload, ``bypass`` skips a guard,
        ``stale`` serves a previous listing, ``slow`` injects latency.
        Each consumption counts its own hit of `point` (one boundary, one
        counter) and is recorded like any injection."""
        with self._lock:
            hit = self.hits.get(point, 0) + 1
            self.hits[point] = hit
            for r in self.rules:
                if r.point == point and r.kind == kind and r.wants(
                    self.seed, hit, {}
                ):
                    r.fired += 1
                    self._record(point, kind)
                    return True
        return False

    def consume_corruption(self, point: str = "ckpt.write") -> bool:
        """True iff a ``torn`` rule fires for this write — the caller (the
        atomic checkpoint writer) then corrupts the file it just wrote,
        simulating a torn write that the CRC footer must catch on load."""
        return self.consume_special(point, "torn")

    def consume_bypass(self, point: str) -> bool:
        """True iff a ``bypass`` rule fires for this hit — the caller then
        SKIPS a guard instead of raising (the `fleet.zombie_write` shape:
        `ckptio.fenced_savez` omits its pre-write lease check, simulating a
        write already past the check when the revocation landed)."""
        return self.consume_special(point, "bypass")

    def _hang(self, point: str) -> None:
        """The hang gate: block until the watchdog cancels us (or the
        plan's own hang_limit_s safety valve), then surface the hang as a
        retriable `WatchdogTimeout` — a hang is just a fault that needs a
        watchdog to become visible."""
        deadline = time.monotonic() + self.hang_limit_s
        with self._cancel_cond:
            gen = self._cancel_gen
            while self._cancel_gen == gen:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cancel_cond.wait(left)
        raise WatchdogTimeout(f"injected hang at {point} converted by watchdog")

    def cancel_hangs(self) -> None:
        """Watchdog entry: release every thread currently parked in a hang
        gate (a no-op for gates entered later — they wait on the NEW
        generation)."""
        with self._cancel_cond:
            self._cancel_gen += 1
            self._cancel_cond.notify_all()

    def injected_total(self) -> int:
        with self._lock:
            return sum(self.injected.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "injected_total": sum(self.injected.values()),
                "injected": dict(self.injected),
            }


# -- global installation -------------------------------------------------------
# One process-wide active plan (NOT thread-local: the service scheduler and
# the supervisor's worker threads must all see it). `maybe_fault` is the
# zero-cost-when-off hot-path check every boundary calls.

_ACTIVE: Optional[FaultPlan] = None


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install `plan` as the process-wide active plan; returns the previous
    one (for restore)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan
    return prev


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


class active:
    """Context manager: `with faults.active(plan): ...` installs the plan
    for the block and restores the previous one after."""

    def __init__(self, plan: Optional[FaultPlan]):
        self._plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> Optional[FaultPlan]:
        self._prev = install_plan(self._plan)
        return self._plan

    def __exit__(self, *exc) -> None:
        install_plan(self._prev)


def maybe_fault(point: str, **ctx) -> None:
    """The injection shim every failure boundary calls. Free when no plan
    is installed (one global read); otherwise defers to the plan."""
    plan = _ACTIVE
    if plan is not None:
        plan.fire(point, ctx)
