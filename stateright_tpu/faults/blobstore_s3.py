"""The managed S3 backend behind the blobstore seam (ROADMAP item 3).

`S3Client` is a `_RetryingClient` over the S3 REST API — pure-stdlib
**SigV4** request signing (hmac/hashlib; no boto3 anywhere near the wire
path), credentials from `faults/creds.py`'s chain (env → shared
credentials file → SDK discovery → IMDS), selected by ``s3://bucket
[/prefix]`` root URIs. The seam's contract maps onto the provider like
this:

- **Conditional put** (`if_absent=True`) → ``If-None-Match: *`` (real S3
  honors it on PUT since 2024-08; a 412 means another writer won — the
  seam's None return).
- **Generation tokens** → derived from the response **ETag** (a stable
  positive int via CRC of the quoted ETag string; S3 has no numeric
  generation, but the seam only needs identity + truthiness, and the
  torn-put negation survives).
- **``.prev`` rotation** → re-derived as a server-side **COPY**
  conditioned on ``x-amz-copy-source-if-match: <etag>`` before the PUT:
  a 412 on the copy means the object changed between HEAD and COPY
  (another writer mid-rotation) and is surfaced as a retryable
  transport error, so the bounded retry re-runs the whole
  HEAD→COPY→PUT sequence — rotation is atomic-or-retried, never half.
- **Throttle fidelity** → S3's ``503 SlowDown``/429 carry
  ``Retry-After``; the base client floors its deterministic backoff on
  it (counted ``retry_after_waits``).
- **Auth rejects** (401/403 — expired STS token, clock-skewed
  signature) → `_auth_retry` invalidates the credential chain and the
  bounded retry re-signs with freshly resolved credentials: an
  expiring token mid-checkpoint degrades to bounded retry, never a
  lost generation.

Endpoint resolution: ``SR_TPU_S3_ENDPOINT`` (the dialect conformance
emulator, `faults/blobdialect.py`) → ``AWS_ENDPOINT_URL`` → the real
``https://s3.<region>.amazonaws.com`` (region from ``AWS_REGION`` /
``AWS_DEFAULT_REGION``, default us-east-1). Requests are path-style
(``/bucket/key``) so one emulator port serves any bucket.

The SigV4 helpers (`amz_quote`, `canonical_query`, `sigv4_signature`,
`signing_key`) are module-level and parameter-pure: the dialect emulator
imports THEM to verify inbound signatures, so client and verifier cannot
drift — a canonicalization bug would still round-trip hermetically, but
the helpers follow the published algorithm and the conformance tests pin
the observable shapes (SignedHeaders coverage, payload-hash check,
error XML)."""

from __future__ import annotations

import calendar
import hashlib
import hmac
import os
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
import zlib
from typing import Optional

from .blobstore import BlobStat, RootedWireStore, _cached_client, _RetryingClient, split_bucket_uri
from .creds import CredentialChain

__all__ = [
    "S3BlobStore",
    "S3Client",
    "amz_quote",
    "canonical_query",
    "etag_generation",
    "s3_client",
    "sigv4_signature",
    "signing_key",
]

#: SigV4 algorithm tag (request header + string-to-sign preamble).
ALGORITHM = "AWS4-HMAC-SHA256"


def amz_quote(s: str) -> str:
    """URI-encode per the SigV4 spec: everything but unreserved chars and
    ``/`` (path segments keep their slashes; query values pass safe="")."""
    return urllib.parse.quote(s, safe="/-_.~")


def canonical_query(params) -> str:
    """The canonical (and actual — one string, no drift) query string:
    key-sorted, strictly encoded."""
    enc = [
        (urllib.parse.quote(str(k), safe="-_.~"),
         urllib.parse.quote(str(v), safe="-_.~"))
        for k, v in (sorted(params.items()) if isinstance(params, dict)
                     else sorted(params))
    ]
    return "&".join(f"{k}={v}" for k, v in enc)


def _hmac_sha256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    """The SigV4 derived key: HMAC chain over date/region/service."""
    k = _hmac_sha256(("AWS4" + secret).encode(), date)
    k = _hmac_sha256(k, region)
    k = _hmac_sha256(k, service)
    return _hmac_sha256(k, "aws4_request")


def sigv4_signature(
    secret: str,
    method: str,
    canonical_uri: str,
    query: str,
    headers: dict,
    signed_headers: str,
    payload_hash: str,
    amz_date: str,
    region: str,
    service: str = "s3",
) -> str:
    """The request signature hex. `headers` maps LOWERCASE names to
    values; `signed_headers` is the ``;``-joined sorted name list (what
    goes in the Authorization header). Shared verbatim by the client and
    the dialect emulator's verifier."""
    canon_headers = "".join(
        f"{name}:{str(headers.get(name, '')).strip()}\n"
        for name in signed_headers.split(";")
    )
    creq = "\n".join(
        (method, canonical_uri, query, canon_headers, signed_headers,
         payload_hash)
    )
    scope = f"{amz_date[:8]}/{region}/{service}/aws4_request"
    sts = "\n".join(
        (ALGORITHM, amz_date, scope,
         hashlib.sha256(creq.encode()).hexdigest())
    )
    return hmac.new(
        signing_key(secret, amz_date[:8], region, service),
        sts.encode(), hashlib.sha256,
    ).hexdigest()


def etag_generation(etag: str) -> int:
    """A stable positive generation token from an ETag string (S3 has no
    numeric generation; the seam needs identity + truthiness + the
    torn-put sign bit, all of which a CRC preserves)."""
    return (zlib.crc32(etag.encode()) & 0x7FFFFFFF) + 1


def _parse_http_date(stamp: str) -> float:
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            base = float(calendar.timegm(time.strptime(stamp, fmt)))
        except ValueError:
            continue
        # timegm drops %f: carry the sub-second part (mtime-LRU
        # consumers — corpus GC — order on it).
        if "." in stamp:
            try:
                base += float("0" + stamp[stamp.index("."):].rstrip("Z"))
            except ValueError:
                pass
        return base
    return 0.0


class S3Client(_RetryingClient):
    """One bucket's SigV4-signing client (cached per (endpoint, bucket)
    — `s3_client`). Names keep the seam's absolute-path convention
    (leading slash); the object key is the name minus it."""

    metrics_source = "blob_s3"

    def __init__(self, endpoint: str, bucket: str, region: str):
        self.bucket = bucket
        self.region = region
        self.endpoint = endpoint.rstrip("/")
        self._chain = CredentialChain("s3")
        super().__init__(f"{self.endpoint}/{bucket}")

    def _auth_retry(self, err) -> bool:
        # A 401/403 (expired STS token, rotated key) is retryable exactly
        # once the chain re-resolves: drop what we signed with.
        self._chain.invalidate()
        return True

    # -- the signed round trip -------------------------------------------------

    def _request(
        self,
        method: str,
        name: str,
        data: Optional[bytes] = None,
        params: Optional[dict] = None,
        extra_headers: Optional[dict] = None,
        timeout: float = 10.0,
    ):
        """One signed request; returns (body, response headers). `name`
        is ""/absolute ("/a/b") — path-style URL under the bucket."""
        creds = self._chain.current()
        canonical_uri = amz_quote("/" + self.bucket + name)
        query = canonical_query(params or {})
        host = urllib.parse.urlsplit(self.endpoint).netloc
        payload = data if data is not None else b""
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        headers = {
            "host": host,
            "x-amz-content-sha256": hashlib.sha256(payload).hexdigest(),
            "x-amz-date": amz_date,
        }
        if creds.session_token:
            headers["x-amz-security-token"] = creds.session_token
        for k, v in (extra_headers or {}).items():
            headers[k.lower()] = v
        signed = ";".join(
            sorted(n for n in headers if n == "host" or n.startswith("x-amz-"))
        )
        sig = sigv4_signature(
            creds.secret_key, method, canonical_uri, query, headers, signed,
            headers["x-amz-content-sha256"], amz_date, self.region,
        )
        scope = f"{amz_date[:8]}/{self.region}/s3/aws4_request"
        headers["authorization"] = (
            f"{ALGORITHM} Credential={creds.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"
        )
        url = self.endpoint + canonical_uri + (f"?{query}" if query else "")
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={k: v for k, v in headers.items() if k != "host"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read(), resp.headers

    def _head_etag(self, name: str) -> Optional[str]:
        """The object's current ETag, or None when absent (a rotation
        no-op, not a failure — must not surface as the put's 404)."""
        try:
            _body, h = self._request("HEAD", name)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        return h.get("ETag", "")

    def _rotate_prev(self, name: str, etag: str) -> None:
        """Server-side COPY of the current generation to ``<name>.prev``,
        conditioned on the ETag the HEAD observed."""
        src = amz_quote("/" + self.bucket + name)
        try:
            self._request(
                "PUT", name + ".prev",
                extra_headers={
                    "x-amz-copy-source": src,
                    "x-amz-copy-source-if-match": etag,
                },
            )
        except urllib.error.HTTPError as e:
            if e.code == 412:
                # The object changed under the rotation (concurrent
                # writer): retryable — the bounded retry re-runs the
                # whole HEAD -> COPY -> PUT sequence.
                raise ConnectionError(
                    f"s3 rotation raced on {name!r} (source etag moved)"
                ) from e
            if e.code == 404:
                return  # source vanished between HEAD and COPY: no .prev
            raise

    # -- raw verbs -------------------------------------------------------------

    def _do_put(
        self, name: str, data: bytes, rotate: bool, if_absent: bool
    ) -> int:
        if rotate:
            etag = self._head_etag(name)
            if etag is not None:
                self._rotate_prev(name, etag)
        headers = {"Content-Type": "application/octet-stream"}
        if if_absent:
            headers["If-None-Match"] = "*"
        _body, h = self._request("PUT", name, data=data, extra_headers=headers)
        return etag_generation(h.get("ETag", ""))

    def _do_get(self, name: str) -> bytes:
        body, _h = self._request("GET", name)
        return body

    def _do_delete(self, name: str) -> bool:
        # S3 DELETE is 204 whether or not the key existed; the seam's
        # bool is best-effort there (GC and retire only log it).
        self._request("DELETE", name)
        return True

    def _do_list(self, prefix: str) -> list:
        params = {"list-type": "2", "prefix": prefix.lstrip("/")}
        body, _h = self._request("GET", "", params=params)
        out = []
        for contents in ET.fromstring(body).iter():
            if not contents.tag.endswith("}Contents") \
                    and contents.tag != "Contents":
                continue
            row = {
                child.tag.rpartition("}")[2]: (child.text or "")
                for child in contents
            }
            out.append(
                BlobStat(
                    "/" + row.get("Key", ""),
                    int(row.get("Size", 0) or 0),
                    _parse_http_date(row.get("LastModified", "")),
                )
            )
        return out

    def _do_exists(self, name: str) -> bool:
        self._request("HEAD", name)
        return True


def s3_client(bucket: str) -> S3Client:
    """The cached per-(endpoint, bucket) client — endpoint + region are
    resolved from the env AT LOOKUP so a test's emulator endpoint selects
    its own client (fresh counters, fresh chain) without touching the
    cache entries of any other server."""
    endpoint = (
        os.environ.get("SR_TPU_S3_ENDPOINT")
        or os.environ.get("AWS_ENDPOINT_URL")
    )
    region = (
        os.environ.get("AWS_REGION")
        or os.environ.get("AWS_DEFAULT_REGION")
        or "us-east-1"
    )
    if not endpoint:
        endpoint = f"https://s3.{region}.amazonaws.com"
    return _cached_client(
        ("s3", endpoint, bucket, region),
        lambda: S3Client(endpoint, bucket, region),
    )


class S3BlobStore(RootedWireStore):
    """The ``s3://bucket[/prefix]`` rooted view (what `blob_backend`
    returns) — all semantics live in `S3Client`."""

    def __init__(self, root_uri: str):
        _scheme, bucket, prefix = split_bucket_uri(root_uri)
        super().__init__(root_uri, s3_client(bucket), prefix)
