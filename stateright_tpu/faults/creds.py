"""Credential lifecycle for the managed object-store backends (ROADMAP
item 3: the S3/GCS residue behind the blobstore seam).

A managed store's requests must be signed, and the signing material has a
LIFECYCLE: it is resolved from somewhere (env vars, key files, an
instance-metadata endpoint), it can EXPIRE mid-run (instance-profile
creds rotate on the order of hours; OAuth access tokens on the order of
minutes), and a refresh can FAIL exactly when the store is also
struggling. This module owns that lifecycle so the blob clients stay
verbs-only:

- `CredentialChain` resolves provider credentials through the standard
  order — **env vars -> key files -> instance-metadata endpoint** — and
  caches the result with its expiry.
- Refresh is **expiry-aware**: a background single-flight refresh kicks
  in `refresh_ahead_s` before expiry (no request ever blocks on a
  refresh that could have happened early), and an access past expiry
  refreshes inline.
- A FAILED refresh degrades through a **grace window**: the stale
  credentials keep serving for `grace_s` past expiry (counted
  ``grace_served`` — a provider-side hiccup must not fail a checkpoint
  that the store would still accept), and only past the window does the
  chain surface `CredentialError` — an OSError, so the blob client's
  bounded retry absorbs it exactly like a transport failure: an
  expiring token mid-checkpoint degrades to bounded retry, never a lost
  generation.
- ``creds.refresh`` is a counted CHAOS POINT (faults/plan.py): an
  injected fault fails one resolve attempt, which is how the grace
  window and the retry degrade are exercised deterministically.

**SDK gating** (the no-new-hard-deps contract): request signing is pure
stdlib (faults/blobstore_s3.py SigV4, the HS256 service-account JWT
below) and never needs an SDK. An installed SDK (boto3 / google.auth)
is used for CREDS DISCOVERY ONLY — and when it is absent the step is a
counted degrade (``sdk_unavailable``) that falls through to the next
rung of the chain. Concretely: a GCS service-account key file carrying
an RSA ``private_key`` requires google.auth to sign (stdlib has no
RS256); key files carrying an ``hmac_secret`` (the emulator shape, and
any HS256-accepting token endpoint) are exchanged with the stdlib JWT.

Metadata endpoints are only probed when their endpoint env var is set
(`AWS_EC2_METADATA_SERVICE_ENDPOINT` / `GCE_METADATA_HOST`): the
hardcoded link-local IMDS address can stall for seconds on a
non-cloud host, and hermetic tests point the env at the dialect
emulator's metadata plane (faults/blobdialect.py) instead.

Stdlib-only, jax-free (like the rest of faults/): the chain runs in the
blobd script, replica subprocesses, and host tooling alike.
"""

from __future__ import annotations

import base64
import calendar
import configparser
import hashlib
import hmac
import json
import os
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from .plan import FaultError, active_plan, maybe_fault

__all__ = [
    "CredentialChain",
    "CredentialError",
    "Credentials",
    "hs256_jwt",
]

#: Providers the chain resolves for (the managed half of
#: knobs.BLOB_BACKENDS; "s3" signs SigV4, "gcs" sends a bearer token).
PROVIDERS = ("s3", "gcs")

#: Metadata-endpoint socket timeout, seconds — the endpoint is
#: link-local/in-proc; anything slower is an outage the retry absorbs.
METADATA_TIMEOUT_S = 2.0


class CredentialError(OSError):
    """No usable credentials (every chain rung failed / grace expired).
    An OSError so the blob client's bounded retry + every caller's
    degrade path (resume-fresh, cold corpus, counted publish fault)
    absorb it without new handling."""


@dataclass
class Credentials:
    """One resolved credential set. S3 fills access_key/secret_key
    (+ session_token); GCS fills token (an OAuth2 bearer). `expiry` is
    epoch seconds (None = never expires); `source` names the chain rung
    that produced it (env | file | sdk | metadata)."""

    provider: str
    access_key: str = ""
    secret_key: str = ""
    session_token: str = ""
    token: str = ""
    expiry: Optional[float] = None
    source: str = ""

    def expires_in(self, now: Optional[float] = None) -> float:
        if self.expiry is None:
            return float("inf")
        return self.expiry - (time.time() if now is None else now)


def _b64url(raw: bytes) -> bytes:
    return base64.urlsafe_b64encode(raw).rstrip(b"=")


def hs256_jwt(claims: dict, secret: str) -> str:
    """A compact HS256 JWT over `claims` — the stdlib service-account
    grant (RS256 key files need the SDK; see module docstring)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = header + b"." + payload
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return (signing_input + b"." + _b64url(sig)).decode()


def _http_json(req, timeout: float = METADATA_TIMEOUT_S) -> dict:
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _parse_iso8601(stamp: str) -> Optional[float]:
    """AWS Expiration stamps ("2026-08-07T12:00:00Z") -> epoch seconds."""
    for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%M:%S.%fZ"):
        try:
            return float(calendar.timegm(time.strptime(stamp, fmt)))
        except ValueError:
            continue
    return None


@dataclass
class _ChainCounters:
    resolves: int = 0
    refreshes: int = 0
    background_refreshes: int = 0
    refresh_failures: int = 0
    grace_served: int = 0
    invalidated: int = 0
    sdk_unavailable: int = 0
    extra: dict = field(default_factory=dict)


class CredentialChain:
    """One provider's credential resolver + refresh state machine (see
    module docstring). Each managed blob client owns one chain — the
    chain's counters land in the obs REGISTRY "creds" source, and every
    resolve attempt crosses the counted ``creds.refresh`` chaos point."""

    def __init__(
        self,
        provider: str,
        refresh_ahead_s: float = 60.0,
        grace_s: float = 300.0,
    ):
        if provider not in PROVIDERS:
            raise ValueError(
                f"unknown credential provider {provider!r} "
                f"(known: {PROVIDERS})"
            )
        self.provider = provider
        self.refresh_ahead_s = refresh_ahead_s
        self.grace_s = grace_s
        self._lock = threading.Lock()
        self._creds: Optional[Credentials] = None
        self._refreshing = False  # background single-flight latch
        self._c = _ChainCounters()
        from ..obs import REGISTRY

        self._metrics_name = REGISTRY.register("creds", self.metrics)

    def metrics(self) -> dict:
        with self._lock:
            return {
                "resolves": self._c.resolves,
                "refreshes": self._c.refreshes,
                "background_refreshes": self._c.background_refreshes,
                "refresh_failures": self._c.refresh_failures,
                "grace_served": self._c.grace_served,
                "invalidated": self._c.invalidated,
                "sdk_unavailable": self._c.sdk_unavailable,
            }

    # -- lifecycle -------------------------------------------------------------

    def current(self) -> Credentials:
        """The credentials a request should sign with RIGHT NOW. Resolves
        on first use, refreshes in the background ahead of expiry,
        refreshes inline past expiry, serves stale within the grace
        window when a refresh fails, and raises `CredentialError` only
        when nothing usable remains."""
        now = time.time()
        with self._lock:
            creds = self._creds
        if creds is None:
            return self._refresh(blocking=True)
        left = creds.expires_in(now)
        if left > self.refresh_ahead_s:
            return creds
        if left > 0:
            # Still valid: refresh EARLY, off the request path.
            self._kick_background_refresh()
            return creds
        # Expired: refresh inline; a failure degrades through the grace
        # window (stale creds the provider may still accept — counted).
        try:
            return self._refresh(blocking=True)
        except (CredentialError, FaultError, OSError, ValueError):
            if -left <= self.grace_s:
                with self._lock:
                    self._c.grace_served += 1
                return creds
            raise

    def invalidate(self) -> None:
        """The provider rejected a signed request (401/403): whatever we
        are holding is wrong — drop it so the next access re-resolves.
        Called by the blob clients' auth-retry path."""
        with self._lock:
            self._creds = None
            self._c.invalidated += 1

    def _kick_background_refresh(self) -> None:
        with self._lock:
            if self._refreshing:
                return
            self._refreshing = True
            self._c.background_refreshes += 1

        def run():
            try:
                self._refresh(blocking=False)
            except (CredentialError, FaultError, OSError, ValueError):
                pass  # counted; the inline path owns the grace decision
            finally:
                with self._lock:
                    self._refreshing = False

        threading.Thread(target=run, daemon=True).start()

    def _refresh(self, blocking: bool) -> Credentials:
        """One resolve attempt through the chain, on the ``creds.refresh``
        chaos point. Success swaps the cached creds; failure is counted
        (and journaled when a chaos plan is recording) and re-raised for
        the caller's grace/retry decision."""
        try:
            maybe_fault("creds.refresh", provider=self.provider)
            creds = self._resolve()
        except (FaultError, OSError, ValueError) as e:
            with self._lock:
                self._c.refresh_failures += 1
            self._emit_event(ok=0, source=type(e).__name__)
            raise
        with self._lock:
            self._creds = creds
            self._c.refreshes += 1
        self._emit_event(ok=1, source=creds.source)
        return creds

    def _emit_event(self, ok: int, source: str) -> None:
        plan = active_plan()
        events = getattr(plan, "events", None) if plan is not None else None
        if events is None:
            return
        try:
            events.emit(
                "creds.refresh", provider=self.provider, ok=ok, source=source
            )
        except Exception:  # noqa: BLE001 — recording never blocks a refresh
            pass

    # -- the resolution chain --------------------------------------------------

    def _resolve(self) -> Credentials:
        with self._lock:
            self._c.resolves += 1
        steps = (
            self._resolve_s3 if self.provider == "s3" else self._resolve_gcs
        )()
        tried = []
        for name, step in steps:
            creds = step()
            if creds is not None:
                return creds
            tried.append(name)
        raise CredentialError(  # srlint: fault-ok the chaos boundary is _refresh's maybe_fault("creds.refresh"), one frame up — _resolve is its resolution body
            f"no {self.provider} credentials found (tried: "
            f"{', '.join(tried)})"
        )

    def _count_sdk_unavailable(self) -> None:
        with self._lock:
            self._c.sdk_unavailable += 1

    # S3: env -> shared credentials file -> SDK discovery -> IMDS.

    def _resolve_s3(self) -> list:
        return [
            ("env", self._s3_env),
            ("file", self._s3_file),
            ("sdk", self._s3_sdk),
            ("metadata", self._s3_metadata),
        ]

    def _s3_env(self) -> Optional[Credentials]:
        ak = os.environ.get("AWS_ACCESS_KEY_ID")
        sk = os.environ.get("AWS_SECRET_ACCESS_KEY")
        if not (ak and sk):
            return None
        return Credentials(
            "s3", access_key=ak, secret_key=sk,
            session_token=os.environ.get("AWS_SESSION_TOKEN", ""),
            source="env",
        )

    def _s3_file(self) -> Optional[Credentials]:
        path = os.environ.get(
            "AWS_SHARED_CREDENTIALS_FILE",
            os.path.expanduser("~/.aws/credentials"),
        )
        if not os.path.isfile(path):
            return None
        cp = configparser.ConfigParser()
        try:
            cp.read(path)
        except configparser.Error:
            return None
        profile = os.environ.get("AWS_PROFILE", "default")
        if not cp.has_section(profile):
            return None
        sec = cp[profile]
        ak = sec.get("aws_access_key_id")
        sk = sec.get("aws_secret_access_key")
        if not (ak and sk):
            return None
        return Credentials(
            "s3", access_key=ak, secret_key=sk,
            session_token=sec.get("aws_session_token", ""), source="file",
        )

    def _s3_sdk(self) -> Optional[Credentials]:
        # Discovery ONLY (never signing): an installed boto3 may know a
        # source this chain does not (SSO caches, process providers).
        try:
            import boto3  # noqa: F401 — optional, gated
        except ImportError:
            self._count_sdk_unavailable()
            return None
        try:
            found = boto3.session.Session().get_credentials()
        except Exception:  # noqa: BLE001 — SDK discovery is best-effort
            return None
        if found is None:
            return None
        frozen = found.get_frozen_credentials()
        return Credentials(
            "s3", access_key=frozen.access_key, secret_key=frozen.secret_key,
            session_token=frozen.token or "", source="sdk",
        )

    def _s3_metadata(self) -> Optional[Credentials]:
        endpoint = os.environ.get("AWS_EC2_METADATA_SERVICE_ENDPOINT")
        if not endpoint:
            return None
        endpoint = endpoint.rstrip("/")
        headers = {}
        try:  # IMDSv2 session token; fall back to v1 when refused
            req = urllib.request.Request(
                endpoint + "/latest/api/token", method="PUT",
                headers={"X-aws-ec2-metadata-token-ttl-seconds": "21600"},
            )
            with urllib.request.urlopen(
                req, timeout=METADATA_TIMEOUT_S
            ) as resp:
                headers["X-aws-ec2-metadata-token"] = resp.read().decode()
        except OSError:
            pass
        base = endpoint + "/latest/meta-data/iam/security-credentials/"
        with urllib.request.urlopen(
            urllib.request.Request(base, headers=headers),
            timeout=METADATA_TIMEOUT_S,
        ) as resp:
            role = resp.read().decode().splitlines()[0].strip()
        out = _http_json(
            urllib.request.Request(
                base + urllib.parse.quote(role), headers=headers
            )
        )
        expiry = _parse_iso8601(str(out.get("Expiration", "")))
        return Credentials(
            "s3",
            access_key=out["AccessKeyId"],
            secret_key=out["SecretAccessKey"],
            session_token=out.get("Token", ""),
            expiry=expiry,
            source="metadata",
        )

    # GCS: env token -> service-account key file -> SDK -> metadata.

    def _resolve_gcs(self) -> list:
        return [
            ("env", self._gcs_env),
            ("file", self._gcs_file),
            ("sdk", self._gcs_sdk),
            ("metadata", self._gcs_metadata),
        ]

    def _gcs_env(self) -> Optional[Credentials]:
        tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if not tok:
            return None
        return Credentials("gcs", token=tok, source="env")

    def _gcs_file(self) -> Optional[Credentials]:
        path = os.environ.get("GOOGLE_APPLICATION_CREDENTIALS")
        if not (path and os.path.isfile(path)):
            return None
        try:
            with open(path, "r") as f:
                info = json.load(f)
        except (OSError, ValueError):
            return None
        if info.get("hmac_secret") and info.get("client_email"):
            return self._gcs_jwt_grant(info)
        if info.get("private_key"):
            # RS256 signing needs the SDK; stdlib cannot. Counted degrade
            # to the next chain rung — documented in the README matrix.
            try:
                import google.auth  # noqa: F401 — optional, gated
            except ImportError:
                self._count_sdk_unavailable()
                return None
            return self._gcs_sdk()
        return None

    def _gcs_jwt_grant(self, info: dict) -> Credentials:
        """Exchange an HS256 service-account JWT at the key file's
        token_uri for a bearer token (the stdlib grant; the dialect
        emulator's /token endpoint verifies the signature)."""
        token_uri = info.get(
            "token_uri", "https://oauth2.googleapis.com/token"
        )
        now = int(time.time())
        assertion = hs256_jwt(
            {
                "iss": info["client_email"],
                "scope": "https://www.googleapis.com/auth/devstorage.read_write",
                "aud": token_uri,
                "iat": now,
                "exp": now + 3600,
            },
            info["hmac_secret"],
        )
        body = urllib.parse.urlencode(
            {
                "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
                "assertion": assertion,
            }
        ).encode()
        out = _http_json(
            urllib.request.Request(
                token_uri, data=body, method="POST",
                headers={
                    "Content-Type": "application/x-www-form-urlencoded"
                },
            )
        )
        return Credentials(
            "gcs",
            token=out["access_token"],
            expiry=time.time() + float(out.get("expires_in", 3600)),
            source="file",
        )

    def _gcs_sdk(self) -> Optional[Credentials]:
        try:
            import google.auth
            import google.auth.transport.requests
        except ImportError:
            self._count_sdk_unavailable()
            return None
        try:
            sdk_creds, _project = google.auth.default()
            sdk_creds.refresh(google.auth.transport.requests.Request())
        except Exception:  # noqa: BLE001 — SDK discovery is best-effort
            return None
        expiry = None
        if getattr(sdk_creds, "expiry", None) is not None:
            expiry = calendar.timegm(sdk_creds.expiry.timetuple())
        return Credentials(
            "gcs", token=sdk_creds.token, expiry=expiry, source="sdk"
        )

    def _gcs_metadata(self) -> Optional[Credentials]:
        host = os.environ.get("GCE_METADATA_HOST")
        if not host:
            return None
        if "://" not in host:
            host = "http://" + host
        out = _http_json(
            urllib.request.Request(
                host.rstrip("/")
                + "/computeMetadata/v1/instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"},
            )
        )
        return Credentials(
            "gcs",
            token=out["access_token"],
            expiry=time.time() + float(out.get("expires_in", 3600)),
            source="metadata",
        )
