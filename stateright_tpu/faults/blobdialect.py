"""Provider-dialect conformance emulators for the managed backends
(ROADMAP item 3's harness half; `serve_blobd(dialect="s3"|"gcs")` and
``scripts/blobd.py --dialect`` route here).

The native ``blob://`` emulator proves the seam's semantics; these
servers prove the PROVIDER WIRE PROTOCOLS, so the managed clients
(`faults/blobstore_s3.py` / `faults/blobstore_gcs.py`) are exercised
end-to-end — signing, credential lifecycle, error shapes — without a
cloud account:

- **S3 dialect**: path-style REST with full **SigV4 verification**
  (recomputed from the raw received request via the SAME helpers the
  client signs with — `blobstore_s3.sigv4_signature`; wrong key →
  ``InvalidAccessKeyId``, bad signature → ``SignatureDoesNotMatch``,
  payload-hash mismatch → ``BadDigest``, expired STS session token →
  ``ExpiredToken``, all in S3's error-XML shape), conditional PUT
  (``If-None-Match: *`` → 412 ``PreconditionFailed``), server-side COPY
  with ``x-amz-copy-source-if-match``, ListObjectsV2 XML, and an
  **IMDSv2 plane** (``PUT /latest/api/token`` + role walk) minting
  expiring session credentials.
- **GCS dialect**: the JSON API with **Bearer verification** (401
  ``Invalid Credentials`` JSON), media upload with
  ``ifGenerationMatch=0`` preconditions (412 JSON), ``copyTo`` with
  ``ifSourceGenerationMatch``, real integer generations, an **OAuth
  token endpoint** (``POST /token`` verifying the stdlib HS256
  service-account JWT grant), and a **GCE metadata plane**
  (``Metadata-Flavor: Google``).

Both share the native emulator's store shape (name → {"data", "gen",
"mtime"}), so tests that reach into `handle.store` to corrupt or
inspect payloads work unchanged, plus fault CONTROLS the chaos plan
cannot express because they live server-side:

- `handle.throttle(n, retry_after_s=...)` — next `n` data-plane
  requests are refused provider-style (S3 ``503 SlowDown`` XML / GCS
  ``429 rateLimitExceeded`` JSON) carrying ``Retry-After``, which pins
  the client's backoff-floor behavior.
- `handle.stale_lists(n)` — snapshot the listing NOW; next `n` LISTs
  serve it (the provider-side eventually-consistent window, vs the
  chaos plan's client-side ``stale`` cache).
- `handle.expire_tokens()` — expire every MINTED credential
  server-side (IMDS session creds, OAuth tokens), so the next signed
  request 403/401s and the client must re-resolve mid-run — the
  expiring-token-mid-checkpoint story without wall-clock sleeps.

`handle.env` is the exact environment a client process needs: endpoint
overrides + static credentials + metadata/token endpoints, all pointing
at this server (never at real cloud addresses — hermeticity is the
point)."""

from __future__ import annotations

import hashlib
import hmac
import json
import threading
import time
import urllib.parse
from typing import Optional

from .blobstore_s3 import ALGORITHM, sigv4_signature

__all__ = [
    "DIALECTS",
    "DialectHandle",
    "serve_dialect",
]

DIALECTS = ("s3", "gcs")

#: The static credentials `handle.env` hands to client processes.
STATIC_S3_KEY = "SRTPUTESTKEY"
STATIC_S3_SECRET = "srtpu-test-secret-key"
STATIC_GCS_TOKEN = "srtpu-static-oauth-token"

#: The service account the GCS dialect's /token endpoint accepts (HS256;
#: `handle.service_account_info()` renders the key file).
SA_EMAIL = "srtpu-sa@srtpu-project.example"
SA_SECRET = "srtpu-sa-hmac-secret"

DEFAULT_BUCKET = "srtpu"
IMDS_SESSION_TOKEN = "srtpu-imds-v2-token"
IMDS_ROLE = "srtpu-role"


def _iso(ts: float) -> str:
    # Millisecond precision, like the real providers — mtime-LRU
    # consumers (corpus GC ordering) must see sub-second distinctions.
    ms = int(round((ts % 1.0) * 1000.0)) % 1000
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts)) + (
        f".{ms:03d}Z"
    )


class _DialectState:
    """Everything the handler threads share, under one lock: the object
    store, auth tables, fault-control budgets, counters."""

    def __init__(self, dialect: str, bucket: str, creds_ttl_s: float):
        self.dialect = dialect
        self.bucket = bucket
        self.creds_ttl_s = creds_ttl_s
        self.lock = threading.RLock()
        self.store: dict = {}  # key -> {"data", "gen", "mtime", "etag"}
        self.gen = 0
        self.counters = {
            "requests": 0,
            "auth_failures": 0,
            "throttles": 0,
            "preconditions": 0,
            "stale_served": 0,
            "tokens_minted": 0,
            "copies": 0,
        }
        self.throttle_left = 0
        self.retry_after_s = 0.05
        self.stale_left = 0
        self.stale_snapshot: Optional[list] = None
        self.minted = 0
        # s3: access key -> secret; session token -> expiry epoch.
        self.s3_keys = {STATIC_S3_KEY: STATIC_S3_SECRET}
        self.s3_tokens: dict = {}
        # gcs: bearer token -> expiry epoch (None = never expires).
        self.gcs_tokens: dict = {STATIC_GCS_TOKEN: None}

    def count(self, key: str, n: int = 1) -> None:
        with self.lock:
            self.counters[key] += n

    def put_object(self, key: str, data: bytes) -> dict:
        with self.lock:
            self.gen += 1
            rec = {
                "data": data,
                "gen": self.gen,
                "mtime": time.time(),
                "etag": '"%s"' % hashlib.md5(data).hexdigest(),
            }
            self.store[key] = rec
            return rec

    def listing(self, prefix: str) -> list:
        """(key, rec) rows under `prefix` — from the stale snapshot while
        a stale window is armed, live otherwise."""
        with self.lock:
            if self.stale_left > 0 and self.stale_snapshot is not None:
                self.stale_left -= 1
                self.count("stale_served")
                rows = self.stale_snapshot
            else:
                rows = [(k, dict(rec)) for k, rec in sorted(self.store.items())]
            return [(k, rec) for k, rec in rows if k.startswith(prefix)]

    def take_throttle(self, path: str) -> bool:
        """Consume one throttle budget unit for a data-plane request."""
        with self.lock:
            if self.throttle_left <= 0:
                return False
            self.throttle_left -= 1
            self.count("throttles")
            return True

    def mint_s3_session(self) -> dict:
        with self.lock:
            self.minted += 1
            n = self.minted
            ak = f"SRTPUROLE{n:03d}"
            secret = f"srtpu-role-secret-{n}"
            token = f"srtpu-session-{n}"
            expiry = time.time() + self.creds_ttl_s
            self.s3_keys[ak] = secret
            self.s3_tokens[token] = expiry
            self.count("tokens_minted")
            return {
                "AccessKeyId": ak,
                "SecretAccessKey": secret,
                "Token": token,
                "Expiration": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime(expiry)
                ),
            }

    def mint_gcs_token(self) -> dict:
        with self.lock:
            self.minted += 1
            token = f"srtpu-minted-token-{self.minted}"
            self.gcs_tokens[token] = time.time() + self.creds_ttl_s
            self.count("tokens_minted")
            return {
                "access_token": token,
                "expires_in": self.creds_ttl_s,
                "token_type": "Bearer",
            }


class DialectHandle:
    """serve_dialect's return — see the module docstring for the fault
    controls. Mirrors `blobstore._ServerHandle`'s surface (`store`,
    `root_uri`, `address`, `shutdown`) so fixtures treat every emulator
    uniformly, plus `env` (client environment) and the controls."""

    def __init__(self, httpd, state: _DialectState, thread):
        self.httpd = httpd
        self._state = state
        self.thread = thread

    @property
    def dialect(self) -> str:
        return self._state.dialect

    @property
    def bucket(self) -> str:
        return self._state.bucket

    @property
    def store(self) -> dict:
        return self._state.store

    @property
    def counters(self) -> dict:
        with self._state.lock:
            return dict(self._state.counters)

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"{host}:{port}"

    @property
    def endpoint(self) -> str:
        return f"http://{self.address}"

    @property
    def root_uri(self) -> str:
        scheme = "s3" if self._state.dialect == "s3" else "gs"
        return f"{scheme}://{self._state.bucket}"

    @property
    def env(self) -> dict:
        """The exact client-process environment for this server: endpoint
        override + static credentials + the metadata plane. Install it
        (os.environ / spawn env_extra) before the first blob op."""
        if self._state.dialect == "s3":
            return {
                "SR_TPU_S3_ENDPOINT": self.endpoint,
                "AWS_ACCESS_KEY_ID": STATIC_S3_KEY,
                "AWS_SECRET_ACCESS_KEY": STATIC_S3_SECRET,
                "AWS_EC2_METADATA_SERVICE_ENDPOINT": self.endpoint,
                "AWS_REGION": "us-east-1",
            }
        return {
            "SR_TPU_GCS_ENDPOINT": self.endpoint,
            "GOOGLE_OAUTH_ACCESS_TOKEN": STATIC_GCS_TOKEN,
            "GCE_METADATA_HOST": self.endpoint,
        }

    def service_account_info(self) -> dict:
        """A GCS service-account key file body (the HS256/stdlib shape)
        whose token_uri points at THIS server's /token endpoint."""
        return {
            "type": "service_account",
            "client_email": SA_EMAIL,
            "hmac_secret": SA_SECRET,
            "token_uri": self.endpoint + "/token",
        }

    # -- fault controls --------------------------------------------------------

    def throttle(self, n: int, retry_after_s: float = 0.05) -> None:
        with self._state.lock:
            self._state.throttle_left = int(n)
            self._state.retry_after_s = float(retry_after_s)

    def stale_lists(self, n: int) -> None:
        with self._state.lock:
            self._state.stale_snapshot = [
                (k, dict(rec))
                for k, rec in sorted(self._state.store.items())
            ]
            self._state.stale_left = int(n)

    def expire_tokens(self) -> None:
        """Expire every MINTED credential server-side (static env creds
        stay valid): the next request signed with one gets the
        provider's auth reject, forcing the client chain to re-resolve
        mid-run."""
        cutoff = time.time() - 1.0
        with self._state.lock:
            for token in self._state.s3_tokens:
                self._state.s3_tokens[token] = cutoff
            for token, expiry in self._state.gcs_tokens.items():
                if expiry is not None:
                    self._state.gcs_tokens[token] = cutoff

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.thread is not None:
            self.thread.join(timeout=5.0)


def serve_dialect(
    dialect: str,
    address: str = "localhost:0",
    block: bool = False,
    bucket: str = DEFAULT_BUCKET,
    creds_ttl_s: float = 3600.0,
):
    """Start one provider-dialect emulator ("s3" or "gcs"; "gs" is
    accepted as an alias since that is the backend/scheme name)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if dialect == "gs":
        dialect = "gcs"
    if dialect not in DIALECTS:
        raise ValueError(
            f"unknown dialect {dialect!r} (known: {DIALECTS})"
        )
    state = _DialectState(dialect, bucket, creds_ttl_s)

    class _Base(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n) if n else b""

        def _send(self, code: int, body: bytes, ctype: str, headers=()):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            if self.command != "HEAD":
                self.wfile.write(body)

        def _json(self, obj, code: int = 200, headers=()):
            self._send(
                code, json.dumps(obj).encode(), "application/json", headers
            )

    class S3Handler(_Base):
        """Path-style S3 REST + the IMDSv2 metadata plane."""

        def _error(self, code: int, s3_code: str, msg: str, headers=()):
            body = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                f"<Error><Code>{s3_code}</Code><Message>{msg}</Message>"
                "</Error>"
            ).encode()
            self._send(code, body, "application/xml", headers)

        def _auth_ok(self, body: bytes) -> bool:
            """Verify the inbound SigV4 signature against the key table,
            recomputing with the client's own helpers."""
            h = self.headers
            auth = h.get("Authorization", "")
            if not auth.startswith(ALGORITHM):
                state.count("auth_failures")
                self._error(403, "AccessDenied", "missing SigV4 authorization")
                return False
            try:
                parts = dict(
                    p.strip().split("=", 1)
                    for p in auth[len(ALGORITHM):].strip().split(",")
                )
                ak, date, region, service, _term = \
                    parts["Credential"].split("/")
                signed = parts["SignedHeaders"]
                sig = parts["Signature"]
            except (KeyError, ValueError):
                state.count("auth_failures")
                self._error(403, "AccessDenied", "malformed authorization")
                return False
            with state.lock:
                secret = state.s3_keys.get(ak)
            if secret is None:
                state.count("auth_failures")
                self._error(
                    403, "InvalidAccessKeyId",
                    f"access key {ak} does not exist",
                )
                return False
            token = h.get("x-amz-security-token")
            if token is not None or ak.startswith("SRTPUROLE"):
                with state.lock:
                    expiry = state.s3_tokens.get(token)
                if expiry is None:
                    state.count("auth_failures")
                    self._error(403, "InvalidToken", "unknown security token")
                    return False
                if expiry <= time.time():
                    state.count("auth_failures")
                    self._error(
                        403, "ExpiredToken",
                        "the provided security token has expired",
                    )
                    return False
            payload_hash = h.get("x-amz-content-sha256", "")
            if hashlib.sha256(body).hexdigest() != payload_hash:
                state.count("auth_failures")
                self._error(
                    400, "BadDigest", "payload hash does not match body"
                )
                return False
            path, _, query = self.path.partition("?")
            headers_map = {k.lower(): v for k, v in h.items()}
            expect = sigv4_signature(
                secret, self.command, path, query, headers_map, signed,
                payload_hash, h.get("x-amz-date", ""), region, service,
            )
            if not hmac.compare_digest(expect, sig):
                state.count("auth_failures")
                self._error(
                    403, "SignatureDoesNotMatch",
                    "the request signature we calculated does not match",
                )
                return False
            return True

        def _gate(self, body: bytes) -> bool:
            """Auth + throttle for one data-plane request."""
            state.count("requests")
            if not self._auth_ok(body):
                return False
            if state.take_throttle(self.path):
                self._error(
                    503, "SlowDown", "please reduce your request rate",
                    headers=(("Retry-After", str(state.retry_after_s)),),
                )
                return False
            return True

        def _key(self) -> Optional[str]:
            """The object key under the bucket, or None off-bucket."""
            path = urllib.parse.unquote(self.path.partition("?")[0])
            bucket_root = "/" + state.bucket
            if path == bucket_root:
                return ""
            if not path.startswith(bucket_root + "/"):
                return None
            return path[len(bucket_root) + 1:]

        # -- IMDSv2 plane ------------------------------------------------------

        def _imds(self) -> bool:
            path = self.path.partition("?")[0]
            if not path.startswith("/latest/"):
                return False
            state.count("requests")
            if self.command == "PUT" and path == "/latest/api/token":
                self._send(
                    200, IMDS_SESSION_TOKEN.encode(), "text/plain"
                )
                return True
            base = "/latest/meta-data/iam/security-credentials/"
            if self.command == "GET" and path == base:
                self._send(200, IMDS_ROLE.encode(), "text/plain")
                return True
            if self.command == "GET" and path == base + IMDS_ROLE:
                self._json(state.mint_s3_session())
                return True
            self._send(404, b"not found", "text/plain")
            return True

        # -- verbs -------------------------------------------------------------

        def do_PUT(self):
            if self._imds():
                return
            body = self._body()
            if not self._gate(body):
                return
            key = self._key()
            if not key:
                self._error(404, "NoSuchKey", "no such key")
                return
            src_hdr = self.headers.get("x-amz-copy-source")
            if src_hdr is not None:
                self._copy(key, src_hdr)
                return
            with state.lock:
                cur = state.store.get(key)
                if self.headers.get("If-None-Match") == "*" \
                        and cur is not None:
                    state.count("preconditions")
                    self._error(
                        412, "PreconditionFailed",
                        "at least one precondition did not hold",
                    )
                    return
                rec = state.put_object(key, body)
            self._send(200, b"", "application/xml",
                       headers=(("ETag", rec["etag"]),))

        def _copy(self, dst: str, src_hdr: str) -> None:
            src = urllib.parse.unquote(src_hdr)
            bucket_root = "/" + state.bucket + "/"
            if src.startswith(bucket_root):
                src = src[len(bucket_root):]
            if_match = self.headers.get("x-amz-copy-source-if-match")
            with state.lock:
                rec = state.store.get(src)
                if rec is None:
                    self._error(404, "NoSuchKey", "copy source missing")
                    return
                if if_match is not None and rec["etag"] != if_match:
                    state.count("preconditions")
                    self._error(
                        412, "PreconditionFailed",
                        "copy source etag does not match",
                    )
                    return
                out = state.put_object(dst, rec["data"])
                state.count("copies")
            body = (
                "<CopyObjectResult><LastModified>"
                f"{_iso(out['mtime'])}</LastModified>"
                f"<ETag>{out['etag']}</ETag></CopyObjectResult>"
            ).encode()
            self._send(200, body, "application/xml")

        def do_GET(self):
            if self._imds():
                return
            if not self._gate(b""):
                return
            key = self._key()
            if key is None:
                self._error(404, "NoSuchBucket", "no such bucket")
                return
            query = dict(
                urllib.parse.parse_qsl(self.path.partition("?")[2])
            )
            if key == "":
                self._list(query.get("prefix", ""))
                return
            with state.lock:
                rec = state.store.get(key)
                data = rec["data"] if rec else None
                etag = rec["etag"] if rec else ""
            if data is None:
                self._error(404, "NoSuchKey", "no such key")
                return
            self._send(200, data, "application/octet-stream",
                       headers=(("ETag", etag),))

        def _list(self, prefix: str) -> None:
            rows = state.listing(prefix)
            parts = ["<?xml version=\"1.0\" encoding=\"UTF-8\"?>",
                     "<ListBucketResult xmlns=\"http://s3.amazonaws.com/"
                     "doc/2006-03-01/\">",
                     f"<Name>{state.bucket}</Name>",
                     f"<KeyCount>{len(rows)}</KeyCount>"]
            for key, rec in rows:
                etag_xml = rec["etag"].replace('"', "&quot;")
                parts.append(
                    "<Contents>"
                    f"<Key>{key}</Key>"
                    f"<Size>{len(rec['data'])}</Size>"
                    f"<LastModified>{_iso(rec['mtime'])}</LastModified>"
                    f"<ETag>{etag_xml}</ETag>"
                    "</Contents>"
                )
            parts.append("</ListBucketResult>")
            self._send(200, "".join(parts).encode(), "application/xml")

        def do_HEAD(self):
            if not self._gate(b""):
                return
            key = self._key()
            with state.lock:
                rec = state.store.get(key) if key else None
            if rec is None:
                # HEAD carries no body — error XML shape not observable.
                self._send(404, b"", "application/xml")
                return
            self._send(200, rec["data"], "application/octet-stream",
                       headers=(("ETag", rec["etag"]),))

        def do_DELETE(self):
            if not self._gate(b""):
                return
            key = self._key()
            with state.lock:
                if key:
                    state.store.pop(key, None)
            self._send(204, b"", "application/xml")

    class GCSHandler(_Base):
        """The GCS JSON API + OAuth token + GCE metadata planes."""

        def _error(self, code: int, reason: str, msg: str, headers=()):
            self._json(
                {
                    "error": {
                        "code": code,
                        "message": msg,
                        "errors": [{"reason": reason, "message": msg}],
                    }
                },
                code, headers,
            )

        def _auth_ok(self) -> bool:
            auth = self.headers.get("Authorization", "")
            if not auth.startswith("Bearer "):
                state.count("auth_failures")
                self._error(401, "authError", "Invalid Credentials")
                return False
            token = auth[len("Bearer "):].strip()
            with state.lock:
                known = token in state.gcs_tokens
                expiry = state.gcs_tokens.get(token)
            if not known or (expiry is not None and expiry <= time.time()):
                state.count("auth_failures")
                self._error(401, "authError", "Invalid Credentials")
                return False
            return True

        def _gate(self) -> bool:
            state.count("requests")
            if not self._auth_ok():
                return False
            if state.take_throttle(self.path):
                self._error(
                    429, "rateLimitExceeded",
                    "rate limit exceeded, retry later",
                    headers=(("Retry-After", str(state.retry_after_s)),),
                )
                return False
            return True

        def _object_json(self, key: str, rec: dict) -> dict:
            return {
                "kind": "storage#object",
                "name": key,
                "bucket": state.bucket,
                "generation": str(rec["gen"]),
                "size": str(len(rec["data"])),
                "updated": _iso(rec["mtime"]),
            }

        # -- token + metadata planes -------------------------------------------

        def _token_plane(self) -> bool:
            path = self.path.partition("?")[0]
            if self.command == "POST" and path == "/token":
                state.count("requests")
                form = dict(
                    urllib.parse.parse_qsl(self._body().decode())
                )
                assertion = form.get("assertion", "")
                try:
                    head, payload, sig = assertion.split(".")
                    import base64 as _b64

                    def unb64(s):
                        return _b64.urlsafe_b64decode(
                            s + "=" * (-len(s) % 4)
                        )

                    claims = json.loads(unb64(payload))
                    expect = hmac.new(
                        SA_SECRET.encode(),
                        f"{head}.{payload}".encode(),
                        hashlib.sha256,
                    ).digest()
                    good = (
                        claims.get("iss") == SA_EMAIL
                        and hmac.compare_digest(
                            _b64.urlsafe_b64encode(expect).rstrip(b"="),
                            sig.encode(),
                        )
                    )
                except (ValueError, KeyError):
                    good = False
                if not good:
                    state.count("auth_failures")
                    self._error(
                        400, "invalid_grant", "JWT signature rejected"
                    )
                    return True
                self._json(state.mint_gcs_token())
                return True
            if (
                self.command == "GET"
                and path == "/computeMetadata/v1/instance/"
                            "service-accounts/default/token"
            ):
                state.count("requests")
                if self.headers.get("Metadata-Flavor") != "Google":
                    self._error(403, "forbidden", "missing Metadata-Flavor")
                    return True
                self._json(state.mint_gcs_token())
                return True
            return False

        # -- routing -----------------------------------------------------------

        def _storage_key(self) -> Optional[str]:
            """The key for ``/storage/v1/b/<bucket>/o/<key>`` paths
            (None for the listing path ``.../o``)."""
            path = self.path.partition("?")[0]
            prefix = f"/storage/v1/b/{state.bucket}/o"
            if not path.startswith(prefix):
                return None
            rest = path[len(prefix):]
            if rest in ("", "/"):
                return None
            return urllib.parse.unquote(rest[1:])

        def do_POST(self):
            if self._token_plane():
                return
            body = self._body()
            if not self._gate():
                return
            path, _, query = self.path.partition("?")
            q = dict(urllib.parse.parse_qsl(query))
            upload_prefix = f"/upload/storage/v1/b/{state.bucket}/o"
            if path == upload_prefix:
                self._upload(body, q)
                return
            key = self._storage_key()
            if key is not None and "/copyTo/" in key:
                self._copy(key, q)
                return
            self._error(404, "notFound", "no such API path")

        def _upload(self, body: bytes, q: dict) -> None:
            key = q.get("name", "")
            if not key:
                self._error(400, "required", "name is required")
                return
            if_gen = q.get(
                "ifGenerationMatch",
                self.headers.get("x-goog-if-generation-match"),
            )
            with state.lock:
                cur = state.store.get(key)
                if if_gen is not None:
                    cur_gen = cur["gen"] if cur is not None else 0
                    if str(cur_gen) != str(if_gen):
                        state.count("preconditions")
                        self._error(
                            412, "conditionNotMet",
                            "at least one precondition did not hold",
                        )
                        return
                rec = state.put_object(key, body)
            self._json(self._object_json(key, rec))

        def _copy(self, key: str, q: dict) -> None:
            src, _, rest = key.partition("/copyTo/")
            # rest is "b/<bucket>/o/<dst>" with dst still quoted inside
            # the original path — unquote already happened; split on the
            # literal markers.
            parts = rest.split("/", 3)
            dst = parts[3] if len(parts) == 4 else ""
            if_src = q.get("ifSourceGenerationMatch")
            with state.lock:
                rec = state.store.get(src)
                if rec is None:
                    self._error(404, "notFound", "copy source missing")
                    return
                if if_src is not None and str(rec["gen"]) != str(if_src):
                    state.count("preconditions")
                    self._error(
                        412, "conditionNotMet",
                        "source generation does not match",
                    )
                    return
                out = state.put_object(dst, rec["data"])
                state.count("copies")
            self._json(self._object_json(dst, out))

        def do_GET(self):
            if self._token_plane():
                return
            if not self._gate():
                return
            path, _, query = self.path.partition("?")
            q = dict(urllib.parse.parse_qsl(query))
            key = self._storage_key()
            if key is None:
                if path.startswith(f"/storage/v1/b/{state.bucket}/o"):
                    rows = state.listing(q.get("prefix", ""))
                    self._json(
                        {
                            "kind": "storage#objects",
                            "items": [
                                self._object_json(k, rec)
                                for k, rec in rows
                            ],
                        }
                    )
                    return
                self._error(404, "notFound", "no such API path")
                return
            with state.lock:
                rec = state.store.get(key)
                rec = dict(rec) if rec is not None else None
            if rec is None:
                self._error(404, "notFound", f"object {key!r} not found")
                return
            if q.get("alt") == "media":
                self._send(200, rec["data"], "application/octet-stream")
                return
            self._json(self._object_json(key, rec))

        def do_DELETE(self):
            if not self._gate():
                return
            key = self._storage_key()
            with state.lock:
                existed = (
                    state.store.pop(key, None) is not None if key else False
                )
            if not existed:
                self._error(404, "notFound", "object not found")
                return
            self._send(204, b"", "application/json")

    handler = S3Handler if dialect == "s3" else GCSHandler
    host, _, port = address.partition(":")
    httpd = ThreadingHTTPServer((host or "localhost", int(port or 0)), handler)
    if block:
        handle = DialectHandle(httpd, state, None)
        try:
            httpd.serve_forever()
        finally:
            httpd.server_close()
        return handle
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return DialectHandle(httpd, state, thread)
