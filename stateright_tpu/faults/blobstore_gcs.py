"""The managed GCS backend behind the blobstore seam (ROADMAP item 3).

`GCSClient` is a `_RetryingClient` over the GCS JSON API — **OAuth2
bearer** auth from `faults/creds.py`'s chain (env token → service-account
key file via the stdlib HS256 JWT grant → SDK discovery → GCE metadata),
selected by ``gs://bucket[/prefix]`` root URIs. No google-cloud-storage
anywhere near the wire path. The seam's contract maps onto the provider
natively — GCS is the backend the seam's generation tokens were shaped
after:

- **Conditional put** (`if_absent=True`) → ``ifGenerationMatch=0`` (and
  the equivalent ``x-goog-if-generation-match: 0`` header): generation 0
  means "only if absent"; a 412 means another writer won — the seam's
  None return.
- **Generation tokens** → GCS object generations verbatim (real int64
  metagenerations from the upload response).
- **``.prev`` rotation** → a server-side ``copyTo`` conditioned on
  ``ifSourceGenerationMatch=<gen>`` before the upload: a 412 on the copy
  means a concurrent writer moved the object and is surfaced as a
  retryable transport error — rotation is atomic-or-retried, never half.
- **Throttle fidelity** → GCS 429 ``rateLimitExceeded`` / 503 carry
  ``Retry-After``; the base client floors its backoff on it.
- **Auth rejects** (401 expired token) → `_auth_retry` invalidates the
  chain and the bounded retry re-sends with a freshly resolved token.

Endpoint resolution: ``SR_TPU_GCS_ENDPOINT`` (the dialect conformance
emulator, `faults/blobdialect.py`) → ``STORAGE_EMULATOR_HOST`` (the
ecosystem convention; scheme optional) → the real
``https://storage.googleapis.com``."""

from __future__ import annotations

import calendar
import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from .blobstore import BlobStat, RootedWireStore, _cached_client, _RetryingClient, split_bucket_uri
from .creds import CredentialChain

__all__ = ["GCSBlobStore", "GCSClient", "gcs_client"]


def _parse_rfc3339(stamp: str) -> float:
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            base = float(calendar.timegm(time.strptime(stamp, fmt)))
        except ValueError:
            continue
        # timegm drops %f: carry the sub-second part (mtime-LRU
        # consumers — corpus GC — order on it).
        if "." in stamp:
            try:
                base += float("0" + stamp[stamp.index("."):].rstrip("Z"))
            except ValueError:
                pass
        return base
    return 0.0


class GCSClient(_RetryingClient):
    """One bucket's JSON-API client (cached per (endpoint, bucket) —
    `gcs_client`). Names keep the seam's absolute-path convention
    (leading slash); the object key is the name minus it, URL-encoded as
    ONE path segment per the JSON API (``o/<quote(key, safe='')>``)."""

    metrics_source = "blob_gcs"

    def __init__(self, endpoint: str, bucket: str):
        self.bucket = bucket
        self.endpoint = endpoint.rstrip("/")
        self._chain = CredentialChain("gcs")
        super().__init__(f"{self.endpoint}/{bucket}")

    def _auth_retry(self, err) -> bool:
        self._chain.invalidate()
        return True

    # -- the authed round trip -------------------------------------------------

    def _key(self, name: str) -> str:
        return urllib.parse.quote(name.lstrip("/"), safe="")

    def _object_url(self, name: str, **params) -> str:
        url = (
            f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
            f"{self._key(name)}"
        )
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return url

    def _request(
        self,
        url: str,
        method: str = "GET",
        data: Optional[bytes] = None,
        extra_headers: Optional[dict] = None,
        timeout: float = 10.0,
    ):
        creds = self._chain.current()
        headers = {"Authorization": f"Bearer {creds.token}"}
        headers.update(extra_headers or {})
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read(), resp.headers

    def _object_generation(self, name: str) -> Optional[int]:
        """The object's current generation, or None when absent (a
        rotation no-op, not a failure)."""
        try:
            body, _h = self._request(self._object_url(name))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        return int(json.loads(body).get("generation", 0))

    def _rotate_prev(self, name: str, gen: int) -> None:
        url = (
            f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
            f"{self._key(name)}/copyTo/b/{self.bucket}/o/"
            f"{self._key(name + '.prev')}"
            f"?ifSourceGenerationMatch={gen}"
        )
        try:
            self._request(url, method="POST", data=b"")
        except urllib.error.HTTPError as e:
            if e.code == 412:
                raise ConnectionError(
                    f"gcs rotation raced on {name!r} (source generation "
                    "moved)"
                ) from e
            if e.code == 404:
                return  # source vanished between stat and copy: no .prev
            raise

    # -- raw verbs -------------------------------------------------------------

    def _do_put(
        self, name: str, data: bytes, rotate: bool, if_absent: bool
    ) -> int:
        if rotate:
            gen = self._object_generation(name)
            if gen is not None:
                self._rotate_prev(name, gen)
        params = {
            "uploadType": "media",
            "name": name.lstrip("/"),
        }
        headers = {"Content-Type": "application/octet-stream"}
        if if_absent:
            params["ifGenerationMatch"] = "0"
            headers["x-goog-if-generation-match"] = "0"
        url = (
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o?"
            + urllib.parse.urlencode(params)
        )
        body, _h = self._request(
            url, method="POST", data=data, extra_headers=headers
        )
        return int(json.loads(body).get("generation", 0))

    def _do_get(self, name: str) -> bytes:
        body, _h = self._request(self._object_url(name, alt="media"))
        return body

    def _do_delete(self, name: str) -> bool:
        try:
            self._request(self._object_url(name), method="DELETE")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False  # LocalFS parity: deleting nothing is False
            raise
        return True

    def _do_list(self, prefix: str) -> list:
        url = (
            f"{self.endpoint}/storage/v1/b/{self.bucket}/o?"
            + urllib.parse.urlencode({"prefix": prefix.lstrip("/")})
        )
        body, _h = self._request(url)
        return [
            BlobStat(
                "/" + item.get("name", ""),
                int(item.get("size", 0) or 0),
                _parse_rfc3339(item.get("updated", "")),
            )
            for item in json.loads(body).get("items", ())
        ]

    def _do_exists(self, name: str) -> bool:
        self._request(self._object_url(name))
        return True


def gcs_client(bucket: str) -> GCSClient:
    """The cached per-(endpoint, bucket) client — endpoint resolved from
    the env AT LOOKUP so a test's emulator endpoint selects its own
    client (fresh counters, fresh chain)."""
    endpoint = (
        os.environ.get("SR_TPU_GCS_ENDPOINT")
        or os.environ.get("STORAGE_EMULATOR_HOST")
        or "https://storage.googleapis.com"
    )
    if "://" not in endpoint:
        endpoint = "http://" + endpoint
    return _cached_client(
        ("gs", endpoint, bucket), lambda: GCSClient(endpoint, bucket)
    )


class GCSBlobStore(RootedWireStore):
    """The ``gs://bucket[/prefix]`` rooted view (what `blob_backend`
    returns) — all semantics live in `GCSClient`."""

    def __init__(self, root_uri: str):
        _scheme, bucket, prefix = split_bucket_uri(root_uri)
        super().__init__(root_uri, gcs_client(bucket), prefix)
