"""Crash-atomic checkpoint I/O with CRC32 verification + generation fallback.

Before this module, every checkpoint writer (`FrontierSearch.checkpoint`,
`ResidentSearch.checkpoint`, `ShardedSearch.checkpoint`, the service's
`Job.spill_frontier`) called `np.savez_compressed(path)` directly: a crash
or full disk mid-write left a truncated archive AT THE FINAL PATH, and the
next `load_checkpoint` raised `BadZipFile` — a partial write poisoned
resume, the exact opposite of what a checkpoint is for.

The fix is the classic tmp+fsync+rename discipline plus an end-to-end
integrity check and one generation of history:

- `atomic_savez` serializes the npz payload in memory, appends a footer
  (magic + payload length + CRC32), writes to ``path + ".tmp"``, fsyncs,
  rotates any existing ``path`` to ``path + ".prev"``, and `os.replace`s
  the tmp into place (atomic on POSIX). A crash at ANY point leaves either
  the old generation at `path`, or the old at `.prev` and the new at
  `path` — never a torn file at a name a loader trusts.
- `read_verified` checks the footer CRC before handing bytes to `np.load`;
  a mismatch (torn write, bit flip) raises `CheckpointCorrupt`. Footerless
  files (pre-fault-plane checkpoints) load unverified for compatibility.
- `load_latest` tries ``path`` then ``path + ".prev"``: a corrupt current
  generation falls back to the previous good one instead of raising, and
  reports which file actually served the restore.

The ``ckpt.write`` injection point (kind ``torn``) corrupts the file right
after a successful write — that is how tests/chaos runs prove the fallback
actually engages.

**Epoch fencing** (the cross-process fleet, service/lease.py): a
checkpoint generation written by a fleet replica is STAMPED with the
writer's lease (member name + monotonically increasing epoch) through
`fenced_savez`, which also re-validates the lease immediately before the
write — a replica the router has declared dead (lease revoked) refuses
its own write instead of publishing a stale generation. `fenced_load_latest`
is the read-side guard: a generation whose stamp a validator rejects
(revoked epoch — the zombie write that raced the revocation through an
already-open fd) is skipped exactly like a torn one, so the newest
generation a loader can be handed is always one written under a lease
that was valid at write time. `fenced_savez(lease=None)` degrades to
`atomic_savez` — standalone engines keep their unfenced (but still
crash-atomic) checkpoints through the same single seam, which is what
lets srlint's SR002 pin every checkpoint write in the repo to this module
or the lease module.
"""

from __future__ import annotations

import io
import os
import struct
import zipfile
import zlib
from typing import Optional

import numpy as np

from .plan import active_plan

#: Footer layout: 8-byte magic, u64 payload length, u32 CRC32 of payload.
MAGIC = b"SRTPCKP1"
_FOOTER = struct.Struct("<8sQI")


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file failed CRC / container verification."""


class LeaseRevoked(RuntimeError):
    """The writer's lease epoch has been revoked (the router declared the
    member dead and requeued its jobs) — the fenced write MUST NOT happen.
    Raised by a lease's `check()` through `fenced_savez`; defined HERE
    (below both the lease store and every fenced caller) so store- and
    service-layer code can catch it by type without importing each other."""


#: Paths this process wrote and fsynced intact (invalidated when the chaos
#: plane corrupts one): rotation can trust them without re-reading and
#: re-CRC-ing the whole previous generation on every checkpoint write.
_WRITTEN_INTACT: set = set()


def normalize_ckpt_path(path: str) -> str:
    """`np.savez` historically appended `.npz` when the suffix was absent;
    keep every writer/loader on the same normalized name."""
    return path if path.endswith(".npz") else path + ".npz"


def content_path(root: str, key: str, kind: str = "corpus") -> str:
    """Content-addressed generation name under `root`: the stable path for
    a checkpoint ADDRESSED BY WHAT IT CONTAINS rather than by who wrote it
    (store/corpus.py warm-start entries; any future shared-generation
    store). Every process that derives the same content key resolves the
    same file, which is what lets fleet replicas share one generation —
    with `.prev` rotation and CRC verification riding along for free,
    since the result is an ordinary `atomic_savez` path. The key is
    sanitized to hex (content keys are blake2b hexdigests; anything else
    is re-hashed) so a key can never escape `root`."""
    key = str(key)
    if not key or any(c not in "0123456789abcdef" for c in key):
        import hashlib

        key = hashlib.blake2b(key.encode(), digest_size=16).hexdigest()
    return os.path.join(root, f"{kind}-{key}.npz")


def atomic_savez(path: str, arrays: dict, keep_prev: bool = True) -> str:
    """Write `arrays` as a compressed npz at `path`, crash-atomically, with
    a CRC32 footer. Rotates an existing `path` to ``path + ".prev"`` first
    (the fallback generation). Returns the path written."""
    path = normalize_ckpt_path(path)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    payload = buf.getvalue()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    # Process-unique tmp name: two PROCESSES may write the same path
    # concurrently (a fleet router re-sealing a generation while the
    # zombie writer it just fenced is still mid-write through an open
    # fd) — a shared ".tmp" would let one writer consume or corrupt the
    # other's staging file; with unique names each write stages
    # privately and the last os.replace wins atomically, which is
    # exactly what the read-side CRC + lease fence are built to judge.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.write(_FOOTER.pack(MAGIC, len(payload), crc))
        f.flush()
        os.fsync(f.fileno())
    if keep_prev and os.path.exists(path):
        # Only a VERIFIED current generation may become the fallback:
        # rotating a torn file into .prev would evict the last good
        # generation. A file this process itself wrote intact is trusted
        # without re-reading it (re-CRC-ing the whole previous generation
        # on every write would double checkpoint I/O).
        if path in _WRITTEN_INTACT:
            os.replace(path, path + ".prev")
        else:
            try:
                read_verified(path)
            except CheckpointCorrupt:
                os.unlink(path)
            else:
                os.replace(path, path + ".prev")
    os.replace(tmp, path)
    _WRITTEN_INTACT.add(path)
    # Make the renames themselves durable (best-effort: not every
    # filesystem supports directory fsync).
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    plan = active_plan()
    if plan is not None and plan.consume_corruption("ckpt.write"):
        _corrupt_file(path, plan.seed)
    return path


def _flip_byte_at(path: str, pos: int) -> None:
    """XOR one byte of `path` in place (shared by the chaos plane's torn
    write and the deliberate test probe)."""
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))


def _corrupt_file(path: str, seed: int) -> None:
    """Deterministically simulate a torn write on `path`: truncate to half
    on even seeds, flip a payload byte on odd seeds. Both must be caught by
    `read_verified` and absorbed by `load_latest`'s fallback."""
    _WRITTEN_INTACT.discard(path)  # no longer trustworthy for rotation
    size = os.path.getsize(path)
    if seed % 2 == 0:
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    else:
        _flip_byte_at(path, max((size - _FOOTER.size) // 2, 0))


def corrupt_one_byte(path: str, frac: float = 0.33) -> None:
    """Flip one payload byte at `frac` of the file — the test/smoke/bench
    corruption probe (the deliberate counterpart of `_corrupt_file`'s
    chaos-plane torn write). Anything protected by the CRC footer must
    detect the flip on its next read."""
    _WRITTEN_INTACT.discard(path)  # no longer trustworthy for rotation
    _flip_byte_at(path, int(os.path.getsize(path) * frac))


def read_verified(path: str):
    """Load one checkpoint file, verifying the CRC footer when present.
    Returns an `NpzFile`-alike; raises `CheckpointCorrupt` on any torn /
    flipped / truncated content, `FileNotFoundError` when absent."""
    with open(path, "rb") as f:
        data = f.read()
    payload = data
    if len(data) >= _FOOTER.size:
        magic, length, crc = _FOOTER.unpack(data[-_FOOTER.size:])
        if magic == MAGIC:
            payload = data[: -_FOOTER.size]
            if length != len(payload) or (
                zlib.crc32(payload) & 0xFFFFFFFF
            ) != crc:
                raise CheckpointCorrupt(
                    f"checkpoint {path} failed CRC verification "
                    "(torn or corrupted write)"
                )
    try:
        return np.load(io.BytesIO(payload), allow_pickle=False)
    except (zipfile.BadZipFile, ValueError, OSError) as e:
        # Footerless legacy file that is ALSO torn — same verdict.
        raise CheckpointCorrupt(f"checkpoint {path} is unreadable: {e}") from e


def load_latest(path: str):
    """Load the newest intact generation of `path`: the file itself, else
    ``path + ".prev"``. Returns ``(npz, served_path)``; raises
    `CheckpointCorrupt` naming every candidate only when none verifies."""
    path = normalize_ckpt_path(path)
    tried: list[str] = []
    for p in (path, path + ".prev"):
        if not os.path.exists(p):
            tried.append(f"{p} (missing)")
            continue
        try:
            return read_verified(p), p
        except CheckpointCorrupt as e:
            tried.append(str(e))
    raise CheckpointCorrupt(
        "no intact checkpoint generation: " + "; ".join(tried)
    )


#: npz keys `fenced_savez` stamps into a generation (and every loader must
#: ignore as payload): the writer's lease identity.
LEASE_STAMP_KEYS = ("lease_member", "lease_epoch")


def lease_stamp(data) -> Optional[tuple]:
    """The `(member, epoch)` lease stamp of a loaded generation, or None
    for an unfenced (standalone-engine / pre-fencing) one."""
    try:
        files = set(getattr(data, "files", ()))
        if not all(k in files for k in LEASE_STAMP_KEYS):
            return None
        member = str(np.asarray(data["lease_member"]).reshape(-1)[0])
        epoch = int(np.asarray(data["lease_epoch"]).reshape(-1)[0])
        return member, epoch
    except (KeyError, ValueError, IndexError):
        return None


def fenced_savez(
    path: str, arrays: dict, lease=None, keep_prev: bool = True
) -> str:
    """`atomic_savez` behind the epoch-fence: with a `lease` (any object
    exposing `.member`, `.epoch`, and a `.check()` that raises once the
    lease is revoked — service/lease.py `Lease`), the write re-validates
    the lease first and stamps the generation with the writer's identity,
    so a fenced loader can reject it if the epoch was revoked meanwhile.
    With `lease=None` this IS `atomic_savez` — the one sanctioned
    checkpoint-write seam for every caller outside this module.

    The ``fleet.zombie_write`` chaos point is consumed here: an injected
    bypass SKIPS the pre-write lease check, simulating a hung-but-alive
    writer that passed the check before revocation and completed the
    write after (the open-fd race) — exactly the stale generation the
    read-side fence must catch."""
    if lease is not None:
        plan = active_plan()
        bypassed = plan is not None and plan.consume_bypass(
            "fleet.zombie_write"
        )
        if not bypassed:
            lease.check()  # raises service.lease.LeaseRevoked when fenced out
        arrays = dict(arrays)
        arrays["lease_member"] = np.asarray(
            [str(lease.member)], dtype=np.str_
        )
        arrays["lease_epoch"] = np.asarray([int(lease.epoch)], np.int64)
    return atomic_savez(path, arrays, keep_prev=keep_prev)


def fenced_load_latest(path: str, validator=None, on_reject=None):
    """`load_latest` behind the epoch-fence: serve the newest intact
    generation whose lease stamp `validator(member, epoch)` accepts.
    Unstamped generations (standalone engines, pre-fencing checkpoints)
    always pass — fencing rejects only writes that PROVE they came from a
    revoked lease. Each rejected generation is reported through
    `on_reject(path, member, epoch)` (the `lease.rejected` accounting) and
    skipped exactly like a torn one, falling back to `.prev`; raises
    `CheckpointCorrupt` naming every candidate when nothing serves."""
    path = normalize_ckpt_path(path)
    if validator is None:
        return load_latest(path)
    tried: list[str] = []
    for p in (path, path + ".prev"):
        if not os.path.exists(p):
            tried.append(f"{p} (missing)")
            continue
        try:
            data = read_verified(p)
        except CheckpointCorrupt as e:
            tried.append(str(e))
            continue
        stamp = lease_stamp(data)
        if stamp is not None and not validator(*stamp):
            if on_reject is not None:
                on_reject(p, *stamp)
            tried.append(
                f"{p} (lease fence: {stamp[0]} epoch {stamp[1]} revoked)"
            )
            continue
        return data, p
    raise CheckpointCorrupt(
        "no intact fenced checkpoint generation: " + "; ".join(tried)
    )


def latest_generation(path: str) -> Optional[str]:
    """The path `load_latest` would serve, or None — a cheap existence
    probe for supervisors deciding between restore and fresh restart."""
    path = normalize_ckpt_path(path)
    for p in (path, path + ".prev"):
        if os.path.exists(p):
            try:
                read_verified(p)
                return p
            except CheckpointCorrupt:
                continue
    return None
