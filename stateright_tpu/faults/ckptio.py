"""Crash-atomic checkpoint I/O with CRC32 verification + generation fallback.

Before this module, every checkpoint writer (`FrontierSearch.checkpoint`,
`ResidentSearch.checkpoint`, `ShardedSearch.checkpoint`, the service's
`Job.spill_frontier`) called `np.savez_compressed(path)` directly: a crash
or full disk mid-write left a truncated archive AT THE FINAL PATH, and the
next `load_checkpoint` raised `BadZipFile` — a partial write poisoned
resume, the exact opposite of what a checkpoint is for.

The fix is the classic tmp+fsync+rename discipline plus an end-to-end
integrity check and one generation of history:

- `atomic_savez` serializes the npz payload in memory, appends a footer
  (magic + payload length + CRC32), writes to ``path + ".tmp"``, fsyncs,
  rotates any existing ``path`` to ``path + ".prev"``, and `os.replace`s
  the tmp into place (atomic on POSIX). A crash at ANY point leaves either
  the old generation at `path`, or the old at `.prev` and the new at
  `path` — never a torn file at a name a loader trusts.
- `read_verified` checks the footer CRC before handing bytes to `np.load`;
  a mismatch (torn write, bit flip) raises `CheckpointCorrupt`. Footerless
  files (pre-fault-plane checkpoints) load unverified for compatibility.
- `load_latest` tries ``path`` then ``path + ".prev"``: a corrupt current
  generation falls back to the previous good one instead of raising, and
  reports which file actually served the restore.

The ``ckpt.write`` injection point (kind ``torn``) corrupts the file right
after a successful write — that is how tests/chaos runs prove the fallback
actually engages.

**Epoch fencing** (the cross-process fleet, service/lease.py): a
checkpoint generation written by a fleet replica is STAMPED with the
writer's lease (member name + monotonically increasing epoch) through
`fenced_savez`, which also re-validates the lease immediately before the
write — a replica the router has declared dead (lease revoked) refuses
its own write instead of publishing a stale generation. `fenced_load_latest`
is the read-side guard: a generation whose stamp a validator rejects
(revoked epoch — the zombie write that raced the revocation through an
already-open fd) is skipped exactly like a torn one, so the newest
generation a loader can be handed is always one written under a lease
that was valid at write time. `fenced_savez(lease=None)` degrades to
`atomic_savez` — standalone engines keep their unfenced (but still
crash-atomic) checkpoints through the same single seam, which is what
lets srlint's SR002 pin every checkpoint write in the repo to this module
or the lease module.

**Blob backend** (faults/blobstore.py, the true multi-host step): every
function here dispatches on the path spelling — a plain/``file://`` path
keeps today's rename/CRC discipline bit-identically, a ``blob://`` URI
routes the same payload+footer bytes through the HTTP object-store client
(conditional puts, server-side ``.prev`` rotation, bounded retry with
seeded deterministic backoff, the ``blob.*`` chaos points). The CRC
footer, the lease stamp, and the current-then-``.prev`` fallback walk are
backend-invariant: a torn blob PUT is rejected and ``.prev`` serves,
exactly like a torn rename. `write_record`/`read_record_latest` extend
the same seam to non-npz CRC'd records (lease files, member-discovery
records), so the store root URI is the only configuration a fleet shares.
"""

from __future__ import annotations

import io
import os
import struct
import zipfile
import zlib
from typing import Optional

import numpy as np

from .blobstore import delete_blob, get_blob, is_blob_uri, put_blob
from .plan import active_plan

#: Footer layout: 8-byte magic, u64 payload length, u32 CRC32 of payload.
MAGIC = b"SRTPCKP1"
_FOOTER = struct.Struct("<8sQI")


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file failed CRC / container verification."""


class LeaseRevoked(RuntimeError):
    """The writer's lease epoch has been revoked (the router declared the
    member dead and requeued its jobs) — the fenced write MUST NOT happen.
    Raised by a lease's `check()` through `fenced_savez`; defined HERE
    (below both the lease store and every fenced caller) so store- and
    service-layer code can catch it by type without importing each other."""


#: Paths this process wrote and fsynced intact (invalidated when the chaos
#: plane corrupts one): rotation can trust them without re-reading and
#: re-CRC-ing the whole previous generation on every checkpoint write.
_WRITTEN_INTACT: set = set()


def normalize_ckpt_path(path: str) -> str:
    """`np.savez` historically appended `.npz` when the suffix was absent;
    keep every writer/loader on the same normalized name. A ``file://``
    scheme is stripped here (the earliest seam every path flows through)
    so downstream code only ever sees plain paths or ``blob://`` URIs."""
    if path.startswith("file://"):
        path = path[len("file://"):] or "/"
    return path if path.endswith(".npz") else path + ".npz"


def content_path(root: str, key: str, kind: str = "corpus") -> str:
    """Content-addressed generation name under `root`: the stable path for
    a checkpoint ADDRESSED BY WHAT IT CONTAINS rather than by who wrote it
    (store/corpus.py warm-start entries; any future shared-generation
    store). Every process that derives the same content key resolves the
    same file, which is what lets fleet replicas share one generation —
    with `.prev` rotation and CRC verification riding along for free,
    since the result is an ordinary `atomic_savez` path. The key is
    sanitized to hex (content keys are blake2b hexdigests; anything else
    is re-hashed) so a key can never escape `root`."""
    key = str(key)
    if not key or any(c not in "0123456789abcdef" for c in key):
        import hashlib

        key = hashlib.blake2b(key.encode(), digest_size=16).hexdigest()
    return os.path.join(root, f"{kind}-{key}.npz")


def atomic_savez(
    path: str,
    arrays: dict,
    keep_prev: bool = True,
    if_absent: bool = False,
) -> Optional[str]:
    """Write `arrays` as a compressed npz at `path`, crash-atomically, with
    a CRC32 footer. Rotates an existing `path` to ``path + ".prev"`` first
    (the fallback generation). Returns the path written.

    `if_absent=True` is the conditional write (the corpus's content-
    addressed idempotence): when an intact generation already exists the
    write is skipped and None returned — on the blob backend this is a
    server-side conditional put (``If-None-Match``), so N fleet replicas
    racing one content key keep exactly ONE generation.

    A ``blob://`` path routes the identical payload+footer bytes through
    the object-store client (faults/blobstore.py): the server rotates
    ``.prev`` atomically, and the only-rotate-verified-generations rule is
    enforced client-side exactly like the local branch below — a torn
    current generation is deleted, never promoted to the fallback."""
    path = normalize_ckpt_path(path)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    payload = buf.getvalue()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if is_blob_uri(path):
        return _blob_savez(
            path, payload + _FOOTER.pack(MAGIC, len(payload), crc),
            keep_prev=keep_prev, if_absent=if_absent,
        )
    if if_absent and latest_generation(path) is not None:
        return None
    # Process-unique tmp name: two PROCESSES may write the same path
    # concurrently (a fleet router re-sealing a generation while the
    # zombie writer it just fenced is still mid-write through an open
    # fd) — a shared ".tmp" would let one writer consume or corrupt the
    # other's staging file; with unique names each write stages
    # privately and the last os.replace wins atomically, which is
    # exactly what the read-side CRC + lease fence are built to judge.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.write(_FOOTER.pack(MAGIC, len(payload), crc))
        f.flush()
        os.fsync(f.fileno())
    if keep_prev and os.path.exists(path):
        # Only a VERIFIED current generation may become the fallback:
        # rotating a torn file into .prev would evict the last good
        # generation. A file this process itself wrote intact is trusted
        # without re-reading it (re-CRC-ing the whole previous generation
        # on every write would double checkpoint I/O).
        if path in _WRITTEN_INTACT:
            os.replace(path, path + ".prev")
        else:
            try:
                read_verified(path)
            except CheckpointCorrupt:
                os.unlink(path)
            else:
                os.replace(path, path + ".prev")
    os.replace(tmp, path)
    _WRITTEN_INTACT.add(path)
    # Make the renames themselves durable (best-effort: not every
    # filesystem supports directory fsync).
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    plan = active_plan()
    if plan is not None and plan.consume_corruption("ckpt.write"):
        _corrupt_file(path, plan.seed)
    return path


def _corrupt_payload(data: bytes, seed: int) -> bytes:
    """The blob twin of `_corrupt_file`: deterministically tear an
    in-memory payload (truncate to half on even seeds, flip a byte on odd
    seeds) before it is uploaded — both must be caught by the CRC check
    and absorbed by the `.prev` fallback."""
    if seed % 2 == 0:
        return data[: max(len(data) // 2, 1)]
    pos = max((len(data) - _FOOTER.size) // 2, 0)
    return data[:pos] + bytes([data[pos] ^ 0xFF]) + data[pos + 1:]


def _blob_savez(
    path: str, data: bytes, keep_prev: bool = True, if_absent: bool = False
) -> Optional[str]:
    """One checkpoint generation onto the blob backend. Mirrors the local
    branch's invariants: only a VERIFIED current generation may rotate
    into ``.prev`` (a torn one is deleted instead — rotating it would
    evict the last good generation), a generation this process itself
    wrote intact is trusted without a round trip, and a consumed
    ``ckpt.write`` torn fault corrupts the uploaded payload (on top of
    the transport-level ``blob.put`` torn point the client consumes)."""
    torn = False
    plan = active_plan()
    if plan is not None and plan.consume_corruption("ckpt.write"):
        data = _corrupt_payload(data, plan.seed)
        torn = True
    rotate = keep_prev
    if path not in _WRITTEN_INTACT and (keep_prev or if_absent):
        # One verified probe of the current generation (paid at most once
        # per path per process — _WRITTEN_INTACT carries the verdict for
        # every later write). It serves two invariants: (a) only a
        # VERIFIED generation may rotate into `.prev` (a torn one is
        # deleted instead — rotating it would evict the last good
        # fallback), and (b) a conditional (`if_absent`) write must treat
        # a TORN current generation as ABSENT: the server's If-None-Match
        # keys on bare existence, so without the delete a single torn
        # first publish would 412-skip every repair attempt forever —
        # the local backend self-heals by overwriting, and the blob
        # backend must match it (backend invariance).
        try:
            data_cur = read_verified(path)
            del data_cur
            if if_absent:
                return None  # intact generation exists: skip, no round trip
        except FileNotFoundError:
            pass  # nothing to rotate; rotate flag is harmless
        except CheckpointCorrupt:
            try:
                delete_blob(path)
            except OSError:
                pass  # unreachable store: rotation best-effort
        except OSError:
            pass  # unreachable store: rotation/conditional best-effort
    gen = put_blob(path, data, rotate=rotate, if_absent=if_absent)
    if gen is None:
        return None  # conditional put lost the race: entry already exists
    if torn or gen < 0:
        # A negated generation is the client saying the UPLOAD was torn
        # (the transport-level blob.put tear): the path must not be
        # trusted for rotation, and the next conditional write must be
        # allowed to probe-and-repair it.
        _WRITTEN_INTACT.discard(path)
    else:
        _WRITTEN_INTACT.add(path)
    return path


def _flip_byte_at(path: str, pos: int) -> None:
    """XOR one byte of `path` in place (shared by the chaos plane's torn
    write and the deliberate test probe)."""
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))


def _corrupt_file(path: str, seed: int) -> None:
    """Deterministically simulate a torn write on `path`: truncate to half
    on even seeds, flip a payload byte on odd seeds. Both must be caught by
    `read_verified` and absorbed by `load_latest`'s fallback."""
    _WRITTEN_INTACT.discard(path)  # no longer trustworthy for rotation
    size = os.path.getsize(path)
    if seed % 2 == 0:
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    else:
        _flip_byte_at(path, max((size - _FOOTER.size) // 2, 0))


def corrupt_one_byte(path: str, frac: float = 0.33) -> None:
    """Flip one payload byte at `frac` of the file — the test/smoke/bench
    corruption probe (the deliberate counterpart of `_corrupt_file`'s
    chaos-plane torn write). Anything protected by the CRC footer must
    detect the flip on its next read."""
    _WRITTEN_INTACT.discard(path)  # no longer trustworthy for rotation
    _flip_byte_at(path, int(os.path.getsize(path) * frac))


def read_verified(path: str):
    """Load one checkpoint file (or blob), verifying the CRC footer when
    present. Returns an `NpzFile`-alike; raises `CheckpointCorrupt` on any
    torn / flipped / truncated content, `FileNotFoundError` when absent
    (both backends — a blob 404 IS a missing file)."""
    if is_blob_uri(path):
        data = get_blob(path)
    else:
        with open(path, "rb") as f:
            data = f.read()
    payload = data
    if len(data) >= _FOOTER.size:
        magic, length, crc = _FOOTER.unpack(data[-_FOOTER.size:])
        if magic == MAGIC:
            payload = data[: -_FOOTER.size]
            if length != len(payload) or (
                zlib.crc32(payload) & 0xFFFFFFFF
            ) != crc:
                raise CheckpointCorrupt(
                    f"checkpoint {path} failed CRC verification "
                    "(torn or corrupted write)"
                )
    try:
        return np.load(io.BytesIO(payload), allow_pickle=False)
    except (zipfile.BadZipFile, ValueError, OSError) as e:
        # Footerless legacy file that is ALSO torn — same verdict.
        raise CheckpointCorrupt(f"checkpoint {path} is unreadable: {e}") from e


def load_latest(path: str):
    """Load the newest intact generation of `path`: the file itself, else
    ``path + ".prev"``. Returns ``(npz, served_path)``; raises
    `CheckpointCorrupt` naming every candidate only when none verifies.
    Backend-agnostic: a blob 404 reads as missing, a blob-store outage
    (retry exhaustion) reads as unavailable — both fall to the next
    candidate, so callers keep their one degrade path."""
    path = normalize_ckpt_path(path)
    tried: list[str] = []
    for p in (path, path + ".prev"):
        try:
            return read_verified(p), p
        except FileNotFoundError:
            tried.append(f"{p} (missing)")
        except CheckpointCorrupt as e:
            tried.append(str(e))
        except OSError as e:
            tried.append(f"{p} (unavailable: {type(e).__name__}: {e})")
    raise CheckpointCorrupt(
        "no intact checkpoint generation: " + "; ".join(tried)
    )


#: npz keys `fenced_savez` stamps into a generation (and every loader must
#: ignore as payload): the writer's lease identity.
LEASE_STAMP_KEYS = ("lease_member", "lease_epoch")


def lease_stamp(data) -> Optional[tuple]:
    """The `(member, epoch)` lease stamp of a loaded generation, or None
    for an unfenced (standalone-engine / pre-fencing) one."""
    try:
        files = set(getattr(data, "files", ()))
        if not all(k in files for k in LEASE_STAMP_KEYS):
            return None
        member = str(np.asarray(data["lease_member"]).reshape(-1)[0])
        epoch = int(np.asarray(data["lease_epoch"]).reshape(-1)[0])
        return member, epoch
    except (KeyError, ValueError, IndexError):
        return None


def fenced_savez(
    path: str,
    arrays: dict,
    lease=None,
    keep_prev: bool = True,
    if_absent: bool = False,
) -> Optional[str]:
    """`atomic_savez` behind the epoch-fence: with a `lease` (any object
    exposing `.member`, `.epoch`, and a `.check()` that raises once the
    lease is revoked — service/lease.py `Lease`), the write re-validates
    the lease first and stamps the generation with the writer's identity,
    so a fenced loader can reject it if the epoch was revoked meanwhile.
    With `lease=None` this IS `atomic_savez` — the one sanctioned
    checkpoint-write seam for every caller outside this module.

    The ``fleet.zombie_write`` chaos point is consumed here: an injected
    bypass SKIPS the pre-write lease check, simulating a hung-but-alive
    writer that passed the check before revocation and completed the
    write after (the open-fd race) — exactly the stale generation the
    read-side fence must catch."""
    if lease is not None:
        plan = active_plan()
        bypassed = plan is not None and plan.consume_bypass(
            "fleet.zombie_write"
        )
        if not bypassed:
            lease.check()  # raises service.lease.LeaseRevoked when fenced out
        arrays = dict(arrays)
        arrays["lease_member"] = np.asarray(
            [str(lease.member)], dtype=np.str_
        )
        arrays["lease_epoch"] = np.asarray([int(lease.epoch)], np.int64)
    return atomic_savez(
        path, arrays, keep_prev=keep_prev, if_absent=if_absent
    )


def fenced_load_latest(path: str, validator=None, on_reject=None):
    """`load_latest` behind the epoch-fence: serve the newest intact
    generation whose lease stamp `validator(member, epoch)` accepts.
    Unstamped generations (standalone engines, pre-fencing checkpoints)
    always pass — fencing rejects only writes that PROVE they came from a
    revoked lease. Each rejected generation is reported through
    `on_reject(path, member, epoch)` (the `lease.rejected` accounting) and
    skipped exactly like a torn one, falling back to `.prev`; raises
    `CheckpointCorrupt` naming every candidate when nothing serves."""
    path = normalize_ckpt_path(path)
    if validator is None:
        return load_latest(path)
    tried: list[str] = []
    for p in (path, path + ".prev"):
        try:
            data = read_verified(p)
        except FileNotFoundError:
            tried.append(f"{p} (missing)")
            continue
        except CheckpointCorrupt as e:
            tried.append(str(e))
            continue
        except OSError as e:
            tried.append(f"{p} (unavailable: {type(e).__name__}: {e})")
            continue
        stamp = lease_stamp(data)
        if stamp is not None and not validator(*stamp):
            if on_reject is not None:
                on_reject(p, *stamp)
            tried.append(
                f"{p} (lease fence: {stamp[0]} epoch {stamp[1]} revoked)"
            )
            continue
        return data, p
    raise CheckpointCorrupt(
        "no intact fenced checkpoint generation: " + "; ".join(tried)
    )


def latest_generation(path: str) -> Optional[str]:
    """The path `load_latest` would serve, or None — a cheap existence
    probe for supervisors deciding between restore and fresh restart
    (both backends; a blob-store outage probes as None, i.e. fresh)."""
    path = normalize_ckpt_path(path)
    for p in (path, path + ".prev"):
        if not is_blob_uri(p) and not os.path.exists(p):
            continue
        try:
            read_verified(p)
            return p
        except (CheckpointCorrupt, OSError):
            continue
    return None


def any_generation(path: str) -> bool:
    """True iff ANY generation candidate exists at `path` (intact or not)
    — the miss-vs-corrupt distinction `CorpusStore.lookup` accounts on,
    without paying a full verified read on the local backend."""
    path = normalize_ckpt_path(path)
    if not is_blob_uri(path):
        return os.path.exists(path) or os.path.exists(path + ".prev")
    from .blobstore import blob_exists

    return blob_exists(path) or blob_exists(path + ".prev")


#: Shared record-footer layout for non-npz CRC'd records (lease files,
#: member-discovery records): payload + (magic, length, CRC32) — the same
#: torn-write detection as checkpoint generations, magic per record kind.
RECORD_FOOTER = _FOOTER


def write_record(path: str, payload: bytes, magic: bytes) -> None:
    """Crash-atomic small-record write, backend-agnostic: payload + CRC
    footer staged through tmp/fsync/rename with unconditional ``.prev``
    rotation on the filesystem, one rotating PUT on the blob backend.
    THE sanctioned write seam for every CRC'd non-npz record (the lease
    store's records, member-discovery records) — srlint SR002 pins raw
    record writes to this module for the same reason it pins npz ones."""
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    data = payload + _FOOTER.pack(magic, len(payload), crc)
    if is_blob_uri(path):
        put_blob(path, data, rotate=True)
        return
    # The LocalFS backend IS the tmp/fsync/rename + `.prev` rotation
    # discipline — one spelling, not three (atomic_savez keeps its own
    # local branch only for the verified-rotation/_WRITTEN_INTACT rules
    # records don't need).
    from .blobstore import LocalFSBlobStore

    d, name = os.path.split(os.path.abspath(path))
    LocalFSBlobStore(d).put(name, data, rotate=True)


def read_record_latest(path: str, magic: bytes) -> tuple:
    """`(payload, any_candidate)` for the newest intact record at `path`
    (``.prev`` fallback included): payload is None when no candidate
    verifies, `any_candidate` says whether anything existed at all (the
    fail-safe distinction the lease store's none-vs-unreadable states
    ride on — an unreachable blob store reads as unreadable, so fencing
    fails SAFE during an outage)."""
    any_candidate = False
    for p in (path, path + ".prev"):
        try:
            if is_blob_uri(p):
                data = get_blob(p)
            else:
                with open(p, "rb") as f:
                    data = f.read()
        except FileNotFoundError:
            continue
        except OSError:
            any_candidate = True  # present-but-unreachable: fail safe
            continue
        any_candidate = True
        if len(data) < _FOOTER.size:
            continue
        m, length, crc = _FOOTER.unpack(data[-_FOOTER.size:])
        payload = data[: -_FOOTER.size]
        if (
            m != magic
            or length != len(payload)
            or (zlib.crc32(payload) & 0xFFFFFFFF) != crc
        ):
            continue
        return payload, True
    return None, any_candidate
