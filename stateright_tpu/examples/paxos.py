"""Single Decree Paxos as actors, validated with a linearizability-tested
register (ref: examples/paxos.rs).

A ballot is (round, leader_id); a proposal is (request_id, requester_id,
value). Phase 1 locks earlier terms and learns previously accepted proposals;
phase 2 drives the chosen proposal to a quorum. The model's history is a
`LinearizabilityTester` fed by the Put/Get/PutOk/GetOk traffic, and the
"linearizable" property simply asks for a valid serialization — the
integration pattern from SURVEY.md §2.5.

Golden: 16,668 unique states with 2 clients / 3 servers on an unordered
non-duplicating network (ref: examples/paxos.rs:327,351).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..actor import Actor, Id, Network, Out, majority, model_peers
from ..actor.model import ActorModel
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

NULL_VALUE = "\x00"  # Value::default() in the reference


# -- internal protocol messages (ref: examples/paxos.rs:66-89) -----------------


@dataclass(frozen=True)
class Prepare:
    ballot: tuple

    def __repr__(self):
        return f"Prepare(ballot={self.ballot!r})"


@dataclass(frozen=True)
class Prepared:
    ballot: tuple
    last_accepted: Optional[tuple]

    def __repr__(self):
        return f"Prepared(ballot={self.ballot!r}, last_accepted={self.last_accepted!r})"


@dataclass(frozen=True)
class Accept:
    ballot: tuple
    proposal: tuple

    def __repr__(self):
        return f"Accept(ballot={self.ballot!r}, proposal={self.proposal!r})"


@dataclass(frozen=True)
class Accepted:
    ballot: tuple

    def __repr__(self):
        return f"Accepted(ballot={self.ballot!r})"


@dataclass(frozen=True)
class Decided:
    ballot: tuple
    proposal: tuple

    def __repr__(self):
        return f"Decided(ballot={self.ballot!r}, proposal={self.proposal!r})"


@dataclass(frozen=True)
class PaxosState:
    """ref: examples/paxos.rs:91-104. `prepares` is a frozenset of
    (peer_id, last_accepted) pairs (at most one entry per peer per ballot);
    `accepts` is a frozenset of peer ids."""

    ballot: tuple
    proposal: Optional[tuple]
    prepares: frozenset
    accepts: frozenset
    accepted: Optional[tuple]
    is_decided: bool


def _max_last_accepted(prepares: frozenset):
    """Highest previously-accepted (ballot, proposal) among prepare replies;
    None ranks lowest (the reference's Option<..>::max,
    ref: examples/paxos.rs:211-217)."""
    best = None
    for _src, last_accepted in prepares:
        if last_accepted is not None and (best is None or last_accepted > best):
            best = last_accepted
    return best


class PaxosActor(Actor):
    """ref: examples/paxos.rs:106-254"""

    def __init__(self, peer_ids):
        self.peer_ids = peer_ids

    def name(self):
        return "Paxos Server"

    def on_start(self, id: Id, out: Out):
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=frozenset(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id: Id, state: PaxosState, src: Id, msg, out: Out):
        if state.is_decided:
            # Only reply once a decision is known locally; an undecided
            # server stays silent (ref: examples/paxos.rs:145-157). The
            # accepted-is-set guard keeps the handler TOTAL (required by the
            # generic device lowering, whose closure pass over-approximates
            # reachable local states): a decided server always has an
            # accepted proposal on every globally reachable path.
            if isinstance(msg, Get) and state.accepted is not None:
                _ballot, (_req, _src, value) = state.accepted
                out.send(src, GetOk(msg.request_id, value))
            return None

        if isinstance(msg, Put) and state.proposal is None:
            ballot = (state.ballot[0] + 1, Id(id))
            proposal = (msg.request_id, Id(src), msg.value)
            out.broadcast(self.peer_ids, Internal(Prepare(ballot)))
            return PaxosState(
                ballot=ballot,
                proposal=proposal,
                # Simulated Prepare/Prepared self-sends.
                prepares=frozenset({(Id(id), state.accepted)}),
                accepts=frozenset(),
                accepted=state.accepted,
                is_decided=False,
            )

        if isinstance(msg, Internal):
            inner = msg.msg
            if isinstance(inner, Prepare) and state.ballot < inner.ballot:
                out.send(
                    src,
                    Internal(Prepared(inner.ballot, state.accepted)),
                )
                return PaxosState(
                    ballot=inner.ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=state.accepted,
                    is_decided=False,
                )
            if isinstance(inner, Prepared) and inner.ballot == state.ballot:
                prepares = state.prepares | {(Id(src), inner.last_accepted)}
                if len(prepares) == majority(len(self.peer_ids) + 1):
                    # Leadership handoff: favor the most recently accepted
                    # proposal from the prepare quorum, else the client's
                    # (ref: examples/paxos.rs:194-226).
                    prev = _max_last_accepted(prepares)
                    proposal = prev[1] if prev is not None else state.proposal
                    out.broadcast(
                        self.peer_ids, Internal(Accept(inner.ballot, proposal))
                    )
                    return PaxosState(
                        ballot=state.ballot,
                        proposal=proposal,
                        prepares=prepares,
                        # Simulated Accept/Accepted self-sends.
                        accepts=frozenset({Id(id)}),
                        accepted=(inner.ballot, proposal),
                        is_decided=False,
                    )
                return PaxosState(
                    ballot=state.ballot,
                    proposal=state.proposal,
                    prepares=prepares,
                    accepts=state.accepts,
                    accepted=state.accepted,
                    is_decided=False,
                )
            if isinstance(inner, Accept) and state.ballot <= inner.ballot:
                out.send(src, Internal(Accepted(inner.ballot)))
                return PaxosState(
                    ballot=inner.ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=(inner.ballot, inner.proposal),
                    is_decided=False,
                )
            if isinstance(inner, Accepted) and inner.ballot == state.ballot:
                accepts = state.accepts | {Id(src)}
                if len(accepts) == majority(len(self.peer_ids) + 1):
                    proposal = state.proposal
                    out.broadcast(
                        self.peer_ids, Internal(Decided(inner.ballot, proposal))
                    )
                    request_id, requester_id, _value = proposal
                    out.send(requester_id, PutOk(request_id))
                    return PaxosState(
                        ballot=state.ballot,
                        proposal=proposal,
                        prepares=state.prepares,
                        accepts=accepts,
                        accepted=state.accepted,
                        is_decided=True,
                    )
                return PaxosState(
                    ballot=state.ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=accepts,
                    accepted=state.accepted,
                    is_decided=False,
                )
            if isinstance(inner, Decided):
                return PaxosState(
                    ballot=inner.ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=(inner.ballot, inner.proposal),
                    is_decided=True,
                )
        return None


@dataclass
class PaxosModelCfg:
    """ref: examples/paxos.rs:256-298"""

    client_count: int
    server_count: int = 3
    network: Network = None

    def into_model(self) -> ActorModel:
        network = (
            self.network
            if self.network is not None
            else Network.new_unordered_nonduplicating()
        )

        def value_chosen(model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != NULL_VALUE:
                    return True
            return False

        model = ActorModel.new(self, LinearizabilityTester(Register(NULL_VALUE)))
        for i in range(self.server_count):
            model.actor(
                RegisterServer(PaxosActor(model_peers(i, self.server_count)))
            )
        for _ in range(self.client_count):
            model.actor(
                RegisterClient(put_count=1, server_count=self.server_count)
            )
        return (
            model.with_init_network(network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                # Dedup-first verdict plane; boolean-identical to
                # `serialized_history() is not None`.
                lambda m, s: s.history.is_consistent(),
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
