"""The reference's example workloads, rebuilt on this framework
(ref: /root/reference/examples/*.rs).

Each module exposes the model (importable for tests and benchmarks); the thin
CLI wrappers live in the repo-level examples/ directory.
"""
