"""Last-write-wins register: a state-based CRDT using `choose_random` /
`on_random` nondeterminism for clock skew and value selection
(ref: examples/lww-register.rs).

The "eventually consistent" property is the CRDT flavor: whenever the network
is quiescent, all replicas agree (transient agreement doesn't count, hence an
`always` over quiescent states rather than an `eventually`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..actor import Actor, Id, Network, Out
from ..actor.model import ActorModel
from ..core.model import Expectation

VALUES = ("A", "B", "C")


@dataclass(frozen=True)
class LwwRegister:
    value: str
    timestamp: int
    updater_id: int

    @staticmethod
    def merge(a: "LwwRegister", b: "LwwRegister") -> "LwwRegister":
        return a if (a.timestamp, a.updater_id) > (b.timestamp, b.updater_id) else b


@dataclass(frozen=True)
class SetValue:
    value: str


@dataclass(frozen=True)
class SetTime:
    time: int


@dataclass(frozen=True)
class LwwActorState:
    register: Optional[LwwRegister]
    local_clock: int
    maximum_used_clock: int


class LwwActor(Actor):
    """ref: examples/lww-register.rs:64-150"""

    def __init__(self, peers):
        self.peers = peers

    def name(self):
        return "LWW"

    def _populate_choices(self, out: Out, time: int) -> None:
        out.choose_random(
            "node_action",
            [SetValue(v) for v in VALUES]
            + [SetTime(time + 1), SetTime(max(0, time - 1))],
        )

    def on_start(self, id: Id, out: Out):
        state = LwwActorState(None, 1000, 1000)
        self._populate_choices(out, state.local_clock)
        return state

    def on_random(self, id: Id, state: LwwActorState, random, out: Out):
        if isinstance(random, SetValue):
            if state.register is not None:
                clock = max(state.local_clock, state.maximum_used_clock + 1)
                register = LwwRegister(random.value, clock, int(id))
                new_state = LwwActorState(register, state.local_clock, clock)
            else:
                register = LwwRegister(random.value, state.local_clock, int(id))
                new_state = LwwActorState(
                    register, state.local_clock, state.maximum_used_clock
                )
            out.broadcast(self.peers, register)
            self._populate_choices(out, new_state.local_clock)
            return new_state
        # SetTime
        new_state = LwwActorState(
            state.register, random.time, state.maximum_used_clock
        )
        self._populate_choices(out, new_state.local_clock)
        return new_state

    def on_msg(self, id: Id, state: LwwActorState, src: Id, msg, out: Out):
        # Always report a (possibly identical) new state: the reference marks
        # the Cow owned unconditionally here, so delivery is never elided as a
        # no-op and the message is always consumed from the network
        # (ref: examples/lww-register.rs:131-149).
        if state.register is not None:
            merged = LwwRegister.merge(state.register, msg)
            return LwwActorState(merged, state.local_clock, state.maximum_used_clock)
        return LwwActorState(msg, state.local_clock, state.maximum_used_clock)


def build_model(num_actors: int) -> ActorModel:
    """ref: examples/lww-register.rs:152-186"""
    nodes = [Id(i) for i in range(num_actors)]

    def eventually_consistent(model, state):
        if len(state.network) == 0:
            regs = [s.register for s in state.actor_states]
            return all(r == regs[0] for r in regs)
        return True

    model = ActorModel.new(None, None)
    for _ in range(num_actors):
        model.actor(LwwActor(peers=nodes))
    return model.with_init_network(
        Network.new_unordered_nonduplicating()
    ).property(Expectation.ALWAYS, "eventually consistent", eventually_consistent)
