"""A non-replicated rewritable register: deliberately not fault-tolerant, and
linearizable only when there is a single server
(ref: examples/single-copy-register.rs).

Goldens: 93 unique states (1 server / 2 clients); 20 with 2 servers, where
both "linearizable" (counterexample) and "value chosen" (example) trigger.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..actor import Actor, Id, Network, Out
from ..actor.model import ActorModel
from ..actor.register import (
    Get,
    GetOk,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

NULL_VALUE = "\x00"


class SingleCopyActor(Actor):
    """ref: examples/single-copy-register.rs:15-46"""

    def on_start(self, id: Id, out: Out):
        return NULL_VALUE

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if isinstance(msg, Put):
            out.send(src, PutOk(msg.request_id))
            return msg.value
        if isinstance(msg, Get):
            out.send(src, GetOk(msg.request_id, state))
            return None
        return None


@dataclass
class SingleCopyModelCfg:
    """ref: examples/single-copy-register.rs:48-88"""

    client_count: int
    server_count: int = 1
    network: Network = None

    def into_model(self) -> ActorModel:
        network = (
            self.network
            if self.network is not None
            else Network.new_unordered_nonduplicating()
        )

        def value_chosen(model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != NULL_VALUE:
                    return True
            return False

        model = ActorModel.new(self, LinearizabilityTester(Register(NULL_VALUE)))
        for _ in range(self.server_count):
            model.actor(RegisterServer(SingleCopyActor()))
        for _ in range(self.client_count):
            model.actor(RegisterClient(put_count=1, server_count=self.server_count))
        return (
            model.with_init_network(network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                # Dedup-first verdict plane; boolean-identical to
                # `serialized_history() is not None`.
                lambda m, s: s.history.is_consistent(),
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
