"""Two-phase commit, transcribed from the TLA+ spec in "Consensus on
Transaction Commit" (Gray & Lamport) — a raw `Model`, no actors
(ref: examples/2pc.rs).

Golden counts: 288 unique states with 3 RMs; 8,832 with 5 (665 with symmetry
reduction) (ref: examples/2pc.rs:149-170).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core.model import Model, Property
from ..symmetry import RewritePlan

WORKING, PREPARED, COMMITTED, ABORTED = "working", "prepared", "committed", "aborted"
TM_INIT, TM_COMMITTED, TM_ABORTED = "init", "committed", "aborted"

# Messages: ("prepared", rm) | "commit" | "abort"


@dataclass(frozen=True)
class TwoPhaseState:
    rm_state: tuple  # per-RM state
    tm_state: str
    tm_prepared: tuple  # per-RM bool
    msgs: frozenset

    def representative(self) -> "TwoPhaseState":
        """Canonicalize under RM permutation (ref: examples/2pc.rs:203-223)."""
        plan = RewritePlan.from_values_to_sort(self.rm_state)
        return TwoPhaseState(
            rm_state=plan.reindex(self.rm_state),
            tm_state=self.tm_state,
            tm_prepared=plan.reindex(self.tm_prepared),
            msgs=frozenset(
                ("prepared", plan.inverse[m[1]]) if isinstance(m, tuple) else m
                for m in self.msgs
            ),
        )


@dataclass
class TwoPhaseSys(Model):
    """ref: examples/2pc.rs:59-147"""

    rm_count: int

    def init_states(self):
        return [
            TwoPhaseState(
                rm_state=(WORKING,) * self.rm_count,
                tm_state=TM_INIT,
                tm_prepared=(False,) * self.rm_count,
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState, actions: list):
        if state.tm_state == TM_INIT and all(state.tm_prepared):
            actions.append("tm_commit")
        if state.tm_state == TM_INIT:
            actions.append("tm_abort")
        for rm in range(self.rm_count):
            if state.tm_state == TM_INIT and ("prepared", rm) in state.msgs:
                actions.append(("tm_rcv_prepared", rm))
            if state.rm_state[rm] == WORKING:
                actions.append(("rm_prepare", rm))
                actions.append(("rm_choose_abort", rm))
            if "commit" in state.msgs:
                actions.append(("rm_rcv_commit", rm))
            if "abort" in state.msgs:
                actions.append(("rm_rcv_abort", rm))

    def next_state(self, state: TwoPhaseState, action):
        rm_state = list(state.rm_state)
        tm_prepared = list(state.tm_prepared)
        tm_state = state.tm_state
        msgs = state.msgs
        if action == "tm_commit":
            tm_state = TM_COMMITTED
            msgs = msgs | {"commit"}
        elif action == "tm_abort":
            tm_state = TM_ABORTED
            msgs = msgs | {"abort"}
        else:
            kind, rm = action
            if kind == "tm_rcv_prepared":
                tm_prepared[rm] = True
            elif kind == "rm_prepare":
                rm_state[rm] = PREPARED
                msgs = msgs | {("prepared", rm)}
            elif kind == "rm_choose_abort":
                rm_state[rm] = ABORTED
            elif kind == "rm_rcv_commit":
                rm_state[rm] = COMMITTED
            elif kind == "rm_rcv_abort":
                rm_state[rm] = ABORTED
        return TwoPhaseState(tuple(rm_state), tm_state, tuple(tm_prepared), msgs)

    def properties(self):
        return [
            Property.sometimes(
                "abort agreement",
                lambda m, s: all(r == ABORTED for r in s.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda m, s: all(r == COMMITTED for r in s.rm_state),
            ),
            Property.always(
                "consistent",
                lambda m, s: not (
                    ABORTED in s.rm_state and COMMITTED in s.rm_state
                ),
            ),
        ]
