"""Shared-memory interleaving models: a data-race demo and its lock fix
(ref: examples/increment.rs, examples/increment_lock.rs).

`IncrementSys` exhibits the classic lost-update race (the "fin" invariant is
violated when two threads read the same shared value). With 2 threads the
space is exactly 13 states, 8 under symmetry reduction — the walkthrough the
reference documents at examples/increment.rs:32-105.

`IncrementLockSys` adds a global lock, restoring the invariant and adding a
"mutex" property.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core.model import Model, Property

# Thread state is (t, pc): thread-local value and program counter.


@dataclass(frozen=True)
class IncrementState:
    i: int  # shared
    s: tuple  # per-thread (t, pc)

    def representative(self) -> "IncrementState":
        return IncrementState(self.i, tuple(sorted(self.s)))


@dataclass
class IncrementSys(Model):
    """ref: examples/increment.rs:108-202"""

    thread_count: int

    def init_states(self):
        return [IncrementState(0, ((0, 1),) * self.thread_count)]

    def actions(self, state: IncrementState, actions: list):
        for tid in range(self.thread_count):
            pc = state.s[tid][1]
            if pc == 1:
                actions.append(("read", tid))
            elif pc == 2:
                actions.append(("write", tid))

    def next_state(self, state: IncrementState, action):
        kind, tid = action
        s = list(state.s)
        if kind == "read":
            s[tid] = (state.i, 2)
            return IncrementState(state.i, tuple(s))
        t = state.s[tid][0]
        s[tid] = (t, 3)
        return IncrementState(t + 1, tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda m, s: sum(1 for (t, pc) in s.s if pc == 3) == s.i,
            )
        ]


@dataclass(frozen=True)
class IncrementLockState:
    i: int
    lock: bool
    s: tuple

    def representative(self) -> "IncrementLockState":
        return IncrementLockState(self.i, self.lock, tuple(sorted(self.s)))


@dataclass
class IncrementLockSys(Model):
    """ref: examples/increment_lock.rs"""

    thread_count: int

    def init_states(self):
        return [IncrementLockState(0, False, ((0, 0),) * self.thread_count)]

    def actions(self, state: IncrementLockState, actions: list):
        for tid in range(self.thread_count):
            pc = state.s[tid][1]
            if pc == 0 and not state.lock:
                actions.append(("lock", tid))
            elif pc == 1:
                actions.append(("read", tid))
            elif pc == 2:
                actions.append(("write", tid))
            elif pc == 3 and state.lock:
                actions.append(("release", tid))

    def next_state(self, state: IncrementLockState, action):
        kind, tid = action
        s = list(state.s)
        t, pc = s[tid]
        if kind == "lock":
            s[tid] = (t, 1)
            return IncrementLockState(state.i, True, tuple(s))
        if kind == "read":
            s[tid] = (state.i, 2)
            return IncrementLockState(state.i, state.lock, tuple(s))
        if kind == "write":
            s[tid] = (t, 3)
            return IncrementLockState(t + 1, state.lock, tuple(s))
        s[tid] = (t, 4)
        return IncrementLockState(state.i, False, tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda m, s: sum(1 for (t, pc) in s.s if pc >= 3) == s.i,
            ),
            Property.always(
                "mutex",
                lambda m, s: sum(1 for (t, pc) in s.s if 1 <= pc < 4) <= 1,
            ),
        ]
