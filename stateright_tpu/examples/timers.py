"""Timer-driven ping actors (ref: examples/timers.rs).

Each pinger sets three recurring timers; Even/Odd timers ping even/odd peers,
NoOp renews itself (and is therefore elided by no-op-with-timer detection).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..actor import Actor, Id, Network, Out, model_peers, model_timeout
from ..actor.model import ActorModel
from ..core.model import Expectation

PING, PONG = "Ping", "Pong"
EVEN, ODD, NOOP = "Even", "Odd", "NoOp"


@dataclass(frozen=True)
class PingerState:
    sent: int
    received: int


class PingerActor(Actor):
    """ref: examples/timers.rs:31-98"""

    def __init__(self, peer_ids):
        self.peer_ids = peer_ids

    def name(self):
        return "Pinger"

    def on_start(self, id: Id, out: Out):
        out.set_timer(EVEN, model_timeout())
        out.set_timer(ODD, model_timeout())
        out.set_timer(NOOP, model_timeout())
        return PingerState(sent=0, received=0)

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if msg == PING:
            out.send(src, PONG)
            return None
        if msg == PONG:
            return PingerState(state.sent, state.received + 1)
        return None

    def on_timeout(self, id: Id, state, timer, out: Out):
        if timer == EVEN:
            out.set_timer(EVEN, model_timeout())
            sent = state.sent
            for dst in self.peer_ids:
                if int(dst) % 2 == 0:
                    sent += 1
                    out.send(dst, PING)
            return PingerState(sent, state.received) if sent != state.sent else None
        if timer == ODD:
            out.set_timer(ODD, model_timeout())
            sent = state.sent
            for dst in self.peer_ids:
                if int(dst) % 2 != 0:
                    sent += 1
                    out.send(dst, PING)
            return PingerState(sent, state.received) if sent != state.sent else None
        # NOOP: renew only — elided by no-op-with-timer detection.
        out.set_timer(NOOP, model_timeout())
        return None


@dataclass
class PingerModelCfg:
    """ref: examples/timers.rs:100-117"""

    server_count: int = 3
    network: Network = None

    def into_model(self) -> ActorModel:
        network = (
            self.network
            if self.network is not None
            else Network.new_unordered_nonduplicating()
        )
        model = ActorModel.new(self, None)
        for i in range(self.server_count):
            model.actor(PingerActor(model_peers(i, self.server_count)))
        return model.with_init_network(network).property(
            Expectation.ALWAYS, "true", lambda m, s: True
        )
