"""Modeling external/user input with a driver actor
(ref: examples/interaction.rs).

A Client actor uses timers to inject increment requests into a Counter actor
and then query it; `target_max_depth(30)` bounds the otherwise unbounded
space. The system is heterogeneous (two different actor types) — the
reference needs the `choice!` machinery for this; here the actor list is
simply mixed.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..actor import Actor, Id, Out, model_timeout
from ..actor.model import ActorModel
from ..core.model import Expectation


@dataclass(frozen=True)
class IncrementRequest:
    amount: int


@dataclass(frozen=True)
class ReportRequest:
    pass


@dataclass(frozen=True)
class ReplyCount:
    count: int


@dataclass(frozen=True)
class CounterState:
    addr: Id
    counter: int


@dataclass(frozen=True)
class InputState:
    wait_cycles: int
    success: bool


CLIENT_INPUT, CLIENT_QUERY = "ClientInput", "ClientQuery"


class Counter(Actor):
    """ref: examples/interaction.rs:88-131"""

    def __init__(self, initial_state: CounterState):
        self.initial_state = initial_state

    def name(self):
        return "Counter"

    def on_start(self, id: Id, out: Out):
        return self.initial_state

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if isinstance(msg, IncrementRequest):
            return CounterState(state.addr, state.counter + msg.amount)
        if isinstance(msg, ReportRequest):
            out.send(src, ReplyCount(state.counter))
            return None
        return None


class Client(Actor):
    """ref: examples/interaction.rs:133-205"""

    def __init__(self, threshold: int, counter_addr: Id):
        self.threshold = threshold
        self.counter_addr = counter_addr

    def name(self):
        return "Client"

    def on_start(self, id: Id, out: Out):
        out.set_timer(CLIENT_INPUT, model_timeout())
        return InputState(wait_cycles=0, success=False)

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if isinstance(msg, ReplyCount) and msg.count >= self.threshold:
            return InputState(state.wait_cycles, True)
        return None

    def on_timeout(self, id: Id, state, timer, out: Out):
        if timer == CLIENT_INPUT:
            # Query after incrementing.
            out.set_timer(CLIENT_QUERY, model_timeout())
            out.send(self.counter_addr, IncrementRequest(3))
            return InputState(state.wait_cycles + 1, state.success)
        if timer == CLIENT_QUERY:
            out.send(self.counter_addr, ReportRequest())
            return InputState(state.wait_cycles + 1, state.success)
        return None


def build_model(threshold: int = 3) -> ActorModel:
    """ref: examples/interaction.rs:20-46"""

    def success_reached(model, state):
        return any(
            isinstance(s, InputState) and s.success for s in state.actor_states
        )

    return (
        ActorModel.new(None, 0)
        .actor(Client(threshold=threshold, counter_addr=Id(1)))
        .actor(Counter(CounterState(addr=Id(1), counter=0)))
        .property(Expectation.EVENTUALLY, "success", success_reached)
    )
