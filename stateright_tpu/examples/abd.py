"""ABD quorum register: linearizable shared memory per Attiya, Bar-Noy & Dolev,
"Sharing Memory Robustly in Message-Passing Systems"
(ref: examples/linearizable-register.rs).

Phase 1 queries a quorum for the highest (logical_clock, id) sequencer; phase 2
records the chosen (seq, value) at a quorum. Reads also perform phase 2
(read-repair) to preserve linearizability.

Golden: 544 unique states with 2 clients / 2 servers on an unordered
non-duplicating network.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..actor import Actor, Id, Network, Out, majority, model_peers
from ..actor.model import ActorModel
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

NULL_VALUE = "\x00"


# -- internal protocol (ref: examples/linearizable-register.rs:27-34) ----------


@dataclass(frozen=True)
class Query:
    request_id: int


@dataclass(frozen=True)
class AckQuery:
    request_id: int
    seq: tuple  # (logical_clock, Id)
    value: str


@dataclass(frozen=True)
class Record:
    request_id: int
    seq: tuple
    value: str


@dataclass(frozen=True)
class AckRecord:
    request_id: int


@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: Id
    write: Optional[str]  # value to write, None for reads
    responses: frozenset  # {(peer_id, (seq, value))}


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: Id
    read: Optional[str]  # value to return for reads, None for writes
    acks: frozenset  # {peer_id}


@dataclass(frozen=True)
class AbdState:
    seq: tuple
    val: str
    phase: Optional[object]


class AbdActor(Actor):
    """ref: examples/linearizable-register.rs:62-204"""

    def __init__(self, peers):
        self.peers = peers

    def name(self):
        return "ABD Server"

    def on_start(self, id: Id, out: Out):
        return AbdState(seq=(0, Id(id)), val=NULL_VALUE, phase=None)

    def on_msg(self, id: Id, state: AbdState, src: Id, msg, out: Out):
        if isinstance(msg, (Put, Get)) and state.phase is None:
            req_id = msg.request_id
            out.broadcast(self.peers, Internal(Query(req_id)))
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=req_id,
                    requester_id=Id(src),
                    write=msg.value if isinstance(msg, Put) else None,
                    responses=frozenset({(Id(id), (state.seq, state.val))}),
                ),
            )

        if not isinstance(msg, Internal):
            return None
        inner = msg.msg

        if isinstance(inner, Query):
            out.send(src, Internal(AckQuery(inner.request_id, state.seq, state.val)))
            return None

        if (
            isinstance(inner, AckQuery)
            and isinstance(state.phase, Phase1)
            and state.phase.request_id == inner.request_id
        ):
            ph = state.phase
            # Keyed by peer: a duplicate AckQuery from the same replica
            # replaces its previous entry rather than double-counting toward
            # the quorum (the reference keeps a HashMap<Id, (Seq, Value)>,
            # ref: examples/linearizable-register.rs:118-131).
            responses = frozenset(
                p for p in ph.responses if p[0] != Id(src)
            ) | {(Id(src), (inner.seq, inner.value))}
            if len(responses) < majority(len(self.peers) + 1):
                return AbdState(state.seq, state.val, Phase1(
                    ph.request_id, ph.requester_id, ph.write, responses
                ))
            # Quorum reached: pick max sequencer, move to phase 2
            # (sequencers are distinct, so the max is unambiguous).
            seq, val = max((sv for _p, sv in responses), key=lambda sv: sv[0])
            read = None
            if ph.write is not None:
                seq = (seq[0] + 1, Id(id))
                val = ph.write
            else:
                read = val
            out.broadcast(self.peers, Internal(Record(ph.request_id, seq, val)))
            # Self-send Record.
            new_seq, new_val = (
                (seq, val) if seq > state.seq else (state.seq, state.val)
            )
            return AbdState(
                seq=new_seq,
                val=new_val,
                phase=Phase2(
                    request_id=ph.request_id,
                    requester_id=ph.requester_id,
                    read=read,
                    acks=frozenset({Id(id)}),  # self-send AckRecord
                ),
            )

        if isinstance(inner, Record):
            out.send(src, Internal(AckRecord(inner.request_id)))
            if inner.seq > state.seq:
                return AbdState(inner.seq, inner.value, state.phase)
            return None

        if (
            isinstance(inner, AckRecord)
            and isinstance(state.phase, Phase2)
            and state.phase.request_id == inner.request_id
            and Id(src) not in state.phase.acks
        ):
            ph = state.phase
            acks = ph.acks | {Id(src)}
            if len(acks) < majority(len(self.peers) + 1):
                return AbdState(state.seq, state.val, Phase2(
                    ph.request_id, ph.requester_id, ph.read, acks
                ))
            if ph.read is not None:
                out.send(ph.requester_id, GetOk(ph.request_id, ph.read))
            else:
                out.send(ph.requester_id, PutOk(ph.request_id))
            return AbdState(state.seq, state.val, None)

        return None


@dataclass
class AbdModelCfg:
    """ref: examples/linearizable-register.rs:207-249"""

    client_count: int
    server_count: int = 3
    network: Network = None

    def into_model(self) -> ActorModel:
        network = (
            self.network
            if self.network is not None
            else Network.new_unordered_nonduplicating()
        )

        def value_chosen(model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != NULL_VALUE:
                    return True
            return False

        model = ActorModel.new(self, LinearizabilityTester(Register(NULL_VALUE)))
        for i in range(self.server_count):
            model.actor(RegisterServer(AbdActor(model_peers(i, self.server_count))))
        for _ in range(self.client_count):
            model.actor(RegisterClient(put_count=1, server_count=self.server_count))
        return (
            model.with_init_network(network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                # is_consistent routes through the dedup-first verdict plane
                # (canonical fingerprints + witness-guided serialization) —
                # boolean-identical to `serialized_history() is not None`.
                lambda m, s: s.history.is_consistent(),
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
