"""Spec-CI: the definition-delta check driver (`python -m stateright_tpu.ci`).

The workflow this exists for: a spec author edits ONE property condition
(or the state-space boundary) of a model that already has a published
corpus entry and wants the verdict of the edited spec NOW — not after a
full cold re-exploration. The driver resolves each model against a
shared corpus directory, lets the service's warm ladder (knobs.WARM_KINDS
via store/warm.py + store/specdelta.py) decide how much of the published
work the edit provably salvages, and reports per model:

- the **rung** served — ``exact`` / ``near`` / ``partial`` / ``delta`` /
  ``cold`` — plus the named **edit class** on the delta rung
  (``properties-only`` | ``boundary-only``),
- the per-property **verdicts** (SOMETIMES discovered?, ALWAYS /
  EVENTUALLY violated?),
- whether the run **published** (growing the corpus for the next edit).

A properties-only edit runs in the time of a verdict re-evaluation over
the published visited set; a boundary widening continues from the
published prefix; an expand/init edit is refused by the classifier
(counted in ``delta_refusals``) and runs cold — slower, never wrong.

Exit status is non-zero when any model REGRESSES: an ALWAYS or
EVENTUALLY property produced a counterexample, or a SOMETIMES property
went undiscovered by a COMPLETE (exhaustive) run — an incomplete run
that merely failed to witness a SOMETIMES is inconclusive, not red.

Model specs name an importable attribute: ``pkg.mod:ATTR`` or
``path/to/file.py:ATTR``, where ATTR is a TensorModel instance or a
zero-argument callable returning one.
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import sys
import time
from typing import Optional

__all__ = ["main", "resolve_model", "check_models"]


def resolve_model(spec: str):
    """``pkg.mod:ATTR`` or ``path.py:ATTR`` -> TensorModel instance (ATTR
    may also be a zero-arg callable, e.g. a model class with defaults)."""
    if ":" not in spec:
        raise ValueError(
            f"model spec {spec!r} must be 'module:attr' or 'file.py:attr'"
        )
    mod_part, attr = spec.rsplit(":", 1)
    if mod_part.endswith(".py"):
        name = "_spec_ci_" + mod_part.replace("/", "_").replace(".", "_")
        loader = importlib.util.spec_from_file_location(name, mod_part)
        if loader is None:
            raise ValueError(f"cannot load {mod_part!r}")
        module = importlib.util.module_from_spec(loader)
        sys.modules[name] = module
        loader.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_part)
    obj = getattr(module, attr)
    from ..tensor.model import TensorModel

    if isinstance(obj, TensorModel):
        return obj
    if callable(obj):
        model = obj()
        if isinstance(model, TensorModel):
            return model
    raise TypeError(
        f"{spec!r} is neither a TensorModel nor a callable returning one"
    )


def _verdicts(model, result) -> list:
    """Per-property (name, expectation, ok, note) rows. SOMETIMES is ok
    when discovered OR the run was cut short (inconclusive, not red);
    ALWAYS/EVENTUALLY are ok exactly when undiscovered (a discovery IS
    the counterexample)."""
    from ..core.model import Expectation

    rows = []
    for p in model.properties():
        found = p.name in result.discoveries
        if p.expectation is Expectation.SOMETIMES:
            if found:
                rows.append((p.name, "sometimes", True, "discovered"))
            elif result.complete:
                rows.append((p.name, "sometimes", False, "never reached"))
            else:
                rows.append(
                    (p.name, "sometimes", True, "inconclusive (incomplete)")
                )
        else:
            kind = p.expectation.value
            if found:
                rows.append((p.name, kind, False, "counterexample"))
            else:
                note = (
                    "holds" if result.complete
                    else "no counterexample (incomplete)"
                )
                rows.append((p.name, kind, True, note))
    return rows


def check_models(models, corpus_dir: str, svc_kw: Optional[dict] = None):
    """Run every (spec, model) through ONE corpus-enabled service and
    return report rows: {spec, rung, delta_class, seconds, states,
    unique, complete, published, verdicts, regressions}."""
    from ..service.api import CheckService

    kw = dict(
        batch_size=1024, table_log2=18, store="tiered", high_water=0.9,
        summary_log2=18, background=False,
    )
    kw.update(svc_kw or {})
    reports = []
    svc = CheckService(corpus_dir=corpus_dir, **kw)
    try:
        for spec, model in models:
            t0 = time.monotonic()
            handle = svc.submit(model)
            svc.drain(timeout=None)
            result = handle.result()
            dt = time.monotonic() - t0
            corpus = (result.detail or {}).get("corpus", {})
            verdicts = _verdicts(model, result)
            reports.append(
                {
                    "spec": spec,
                    "rung": corpus.get("warm_kind") or "cold",
                    "delta_class": corpus.get("delta_class"),
                    "seconds": dt,
                    "states": result.state_count,
                    "unique": result.unique_state_count,
                    "complete": result.complete,
                    "published": corpus.get("published", False),
                    "verdicts": verdicts,
                    "regressions": [v for v in verdicts if not v[2]],
                }
            )
        stats = svc._engine.corpus_stats() or {}
    finally:
        svc.close()
    return reports, stats


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stateright_tpu.ci",
        description=(
            "Spec-CI: check edited model definitions against a warm-start "
            "corpus — a one-line property edit re-runs on the 'delta' "
            "rung instead of cold."
        ),
    )
    parser.add_argument(
        "models", nargs="+",
        help="model specs: pkg.mod:ATTR or path/to/file.py:ATTR",
    )
    parser.add_argument(
        "--corpus", required=True,
        help="corpus directory (shared with the checking service/fleet)",
    )
    parser.add_argument("--batch-size", type=int, default=1024)
    parser.add_argument("--table-log2", type=int, default=18)
    args = parser.parse_args(argv)

    models = [(spec, resolve_model(spec)) for spec in args.models]
    reports, stats = check_models(
        models, args.corpus,
        svc_kw={"batch_size": args.batch_size, "table_log2": args.table_log2},
    )
    red = 0
    for rep in reports:
        rung = rep["rung"]
        if rep["delta_class"]:
            rung += f" ({rep['delta_class']})"
        status = "FAIL" if rep["regressions"] else "ok"
        if rep["regressions"]:
            red += 1
        print(
            f"[{status:>4}] {rep['spec']}: rung={rung} "
            f"states={rep['states']} unique={rep['unique']} "
            f"complete={rep['complete']} published={rep['published']} "
            f"{rep['seconds']:.2f}s"
        )
        for name, kind, ok, note in rep["verdicts"]:
            mark = "+" if ok else "-"
            print(f"       {mark} {kind:<10} {name}: {note}")
    print(
        "corpus: "
        f"delta_hits={stats.get('delta_hits', 0)} "
        f"delta_refusals={stats.get('delta_refusals', 0)} "
        f"component_reuse={stats.get('component_reuse', 0)}"
    )
    return 1 if red else 0
