"""jaxpr auditor: abstract-trace an engine step and pin what the compiler
will actually run.

`jax.make_jaxpr` over the engines' own jitted step kernels with
`ShapeDtypeStruct` operands (CPU-cheap, no device, no data) yields the
exact program XLA receives. This module walks that jaxpr and turns three
classes of silent regression into named, located findings:

- **forbidden ops** — host callbacks (`pure_callback`/`io_callback`/
  `debug_callback`), infeed/outfeed, and in-graph `device_put` transfers
  have no business inside a step region: each is a host round trip per
  step (SURVEY §7's device-residency argument);
- **full-carry gathers** — the r8 regression class: a gather whose
  operand is a whole carry-sized array and whose output moves most of it
  (881 KB/event over PCIe before r8 hand-profiled it). Flagged when the
  operand exceeds ``operand_budget`` bytes AND the output moves more than
  ``gather_frac`` of it — bucket-row probe gathers (big output, small
  operand) stay legal;
- **accidental f64** — any float64 intermediate (the engines are
  u32-native; an f64 is always an upcast leak).

It also accumulates per-step FLOP/byte/transfer totals that
``analysis/anchors.py`` cross-checks against `tensor/costmodel.py` and
tests pin as budgets — a future edit that re-introduces a giant gather
fails CI with an op name and source line, not a slow benchmark three
rounds later.

Accounting model (deterministic, compiler-naive by design): every eqn
reads its operands and writes its outputs once (`bytes`); `flops` uses a
small per-primitive table (elementwise = output size, reductions = input
size, sorts = n log n per operand). `while` bodies count ONCE — the
engines' search loop body is exactly one step, so "loop body once" IS the
per-step cost; `scan` bodies multiply by trip count. XLA fusion makes the
absolute byte number an over-estimate of HBM traffic — budgets pin the
*trend*, the cross-check pins the *order of magnitude*.
"""

from __future__ import annotations

import math
from collections import Counter
from dataclasses import dataclass, field
from typing import Optional

import jax

try:  # jax >= 0.4.x
    from jax.extend import core as jcore
except ImportError:  # pragma: no cover - older images
    from jax import core as jcore  # type: ignore

try:
    from jax._src import source_info_util as _siu
except ImportError:  # pragma: no cover - private-API drift
    _siu = None

#: primitives that are host round trips — never legal inside a step.
CALLBACK_PRIMS = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "host_callback_call",
    "outside_call",
    "infeed",
    "outfeed",
}

#: in-graph host<->device transfers (legal at trace boundaries only).
TRANSFER_PRIMS = {"device_put", "copy_to_host_async"}

#: one-flop-per-output-element primitives.
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "neg", "abs",
    "max", "min", "and", "or", "xor", "not", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "eq", "ne", "lt", "le",
    "gt", "ge", "select_n", "exp", "log", "tanh", "erf", "rsqrt", "sqrt",
    "floor", "ceil", "round", "sign", "clamp", "population_count", "clz",
    "nextafter", "logistic", "square",
}

#: input-sized primitives (reductions).
_REDUCTIONS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "reduce_prod", "argmax", "argmin", "cumsum", "cummax", "cummin",
    "cumprod", "cumlogsumexp", "reduce_precision",
}


@dataclass(frozen=True)
class Violation:
    rule: str  # "callback" | "transfer" | "full-carry-gather" | "f64"
    op: str  # primitive name
    location: str  # "file.py:line" best-effort from eqn source info
    detail: str

    def __str__(self) -> str:
        return f"[{self.rule}] {self.op} at {self.location}: {self.detail}"


@dataclass
class AuditTotals:
    flops: int = 0
    hbm_bytes: int = 0
    ops: Counter = field(default_factory=Counter)

    def add(self, other: "AuditTotals") -> None:
        self.flops += other.flops
        self.hbm_bytes += other.hbm_bytes
        self.ops.update(other.ops)


@dataclass
class AuditReport:
    name: str
    totals: AuditTotals  # whole kernel, while bodies once
    step: AuditTotals  # largest while body (the search loop); == totals
    #                    when the kernel has no loop (frontier's step fn)
    violations: list
    in_bytes: int  # kernel operand footprint
    out_bytes: int  # kernel result footprint
    transfer_bytes: int  # host-resident operands re-uploaded per dispatch

    @property
    def clean(self) -> bool:
        return not self.violations

    def summary(self) -> dict:
        """Flat JSON-able row (bench / smoke output)."""
        return {
            "name": self.name,
            "step_flops": self.step.flops,
            "step_hbm_bytes": self.step.hbm_bytes,
            "total_hbm_bytes": self.totals.hbm_bytes,
            "in_bytes": self.in_bytes,
            "out_bytes": self.out_bytes,
            "transfer_bytes": self.transfer_bytes,
            "gathers": self.step.ops.get("gather", 0),
            "scatters": sum(
                n for p, n in self.step.ops.items() if p.startswith("scatter")
            ),
            "violations": [str(v) for v in self.violations],
        }


def _aval_bytes(aval) -> int:
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:  # tokens / abstract units
        return 0
    return int(size) * dtype.itemsize


def _loc(eqn) -> str:
    if _siu is not None:
        try:
            frame = _siu.user_frame(eqn.source_info)
            if frame is not None:
                return f"{frame.file_name}:{frame.start_line}"
        except Exception:
            pass
    return "unknown"


def _sub_jaxprs(params: dict):
    """(key, ClosedJaxpr) pairs nested in an eqn's params (pjit `jaxpr`,
    while `cond_jaxpr`/`body_jaxpr`, cond `branches`, scan `jaxpr`, custom
    call wrappers)."""
    for key, val in params.items():
        if isinstance(val, (jcore.Jaxpr, jcore.ClosedJaxpr)):
            yield key, val
        elif isinstance(val, (tuple, list)):
            for item in val:
                if isinstance(item, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield key, item


def _raw(j):
    return j.jaxpr if isinstance(j, jcore.ClosedJaxpr) else j


def _eqn_flops(eqn) -> int:
    name = eqn.primitive.name
    out_size = sum(int(getattr(v.aval, "size", 0)) for v in eqn.outvars)
    in_size = sum(int(getattr(v.aval, "size", 0)) for v in eqn.invars)
    if name in _ELEMENTWISE or name == "convert_element_type":
        return out_size
    if name in _REDUCTIONS or name.startswith("reduce_"):
        return in_size
    if name == "sort":
        n = max(
            (int(getattr(v.aval, "size", 0)) for v in eqn.invars), default=0
        )
        return int(in_size * math.log2(max(n, 2)))
    if name == "dot_general":
        # 2 * output * contracted-dim; rare in this codebase.
        (contract, _), _ = eqn.params["dimension_numbers"]
        k = 1
        for d in contract:
            k *= eqn.invars[0].aval.shape[d]
        return 2 * out_size * k
    return 0


def _pallas_call_bytes(eqn) -> int:
    """HBM traffic of one `pallas_call`: per-operand
    max(array bytes, block bytes x grid steps). A PARTITIONED array
    streams itself exactly once (block x grid == array), while a
    REPLICATED block — constant index map, e.g. the fused Bloom summary
    riding into VMEM with every partition — re-streams its block every
    grid step, which the plain operand footprint would under-count by the
    grid factor. Falls back to the plain operand/result footprint when
    the (private) grid_mapping layout does not line up with the eqn's
    operands."""
    avals = [v.aval for v in eqn.invars] + [v.aval for v in eqn.outvars]
    plain = sum(_aval_bytes(a) for a in avals)
    gm = eqn.params.get("grid_mapping")
    try:
        steps = 1
        for g in gm.grid:
            steps *= int(g)
        bms = list(gm.block_mappings)
        if steps <= 1 or len(bms) != len(avals):
            return plain
        total = 0
        for aval, bm in zip(avals, bms):
            arr = _aval_bytes(aval)
            dtype = getattr(aval, "dtype", None)
            bshape = getattr(bm, "block_shape", None)
            if not bshape or dtype is None:
                total += arr
                continue
            belems = 1
            for d in bshape:
                belems *= int(d) if d is not None else 1
            total += max(arr, belems * dtype.itemsize * steps)
        return total
    except Exception:  # pragma: no cover - private-API drift tolerance
        return plain


class _Walker:
    def __init__(
        self,
        *,
        operand_budget: int,
        gather_frac: float,
        callbacks_forbidden: bool,
    ):
        self.operand_budget = operand_budget
        self.gather_frac = gather_frac
        self.callbacks_forbidden = callbacks_forbidden
        self.violations: list = []
        self.while_bodies: list = []  # AuditTotals per while body

    def walk(self, jaxpr) -> AuditTotals:
        totals = AuditTotals()
        for eqn in _raw(jaxpr).eqns:
            name = eqn.primitive.name
            if name == "pallas_call":
                # Learned op signature of the Pallas insert (r12): bill the
                # eqn as a LEAF via its grid-aware operand traffic (each
                # partitioned array streams through VMEM once per call,
                # replicated blocks like the fused Bloom summary once per
                # grid step — costmodel's `insert_stream`/`spill_probe`
                # terms; see _pallas_call_bytes). The kernel jaxpr is still
                # scanned for forbidden ops (callbacks, f64 leaks), but its
                # ref-level loads/stores are VMEM traffic — adding them to
                # the totals would double-bill every block — and its
                # internal probe/retry loops must not masquerade as the
                # engine's search-loop body in `step_mode="loop"`.
                n_wb = len(self.while_bodies)
                for _key, sub in _sub_jaxprs(eqn.params):
                    self.walk(sub)
                del self.while_bodies[n_wb:]
                totals.ops[name] += 1
                totals.hbm_bytes += _pallas_call_bytes(eqn)
                continue
            sub_totals = AuditTotals()
            is_while = name == "while"
            scale = 1
            if name == "scan":
                scale = int(eqn.params.get("length", 1))
            for key, sub in _sub_jaxprs(eqn.params):
                st = self.walk(sub)
                if is_while and key == "body_jaxpr":
                    self.while_bodies.append(st)
                sub_totals.add(st)
            if scale > 1:
                sub_totals.flops *= scale
                sub_totals.hbm_bytes *= scale
                for k in sub_totals.ops:
                    sub_totals.ops[k] *= scale
            totals.add(sub_totals)
            if any(True for _ in _sub_jaxprs(eqn.params)):
                # Container eqn (pjit/while/scan/cond): the cost lives in
                # its sub-jaxprs; counting its own operand footprint would
                # double-bill every loop-carried array — and its outvars
                # re-surface inner dtypes, so dtype checks would re-report
                # every inner f64 once per nesting level.
                self._check_forbidden(eqn, name, container=True)
                continue
            totals.ops[name] += 1
            totals.flops += _eqn_flops(eqn)
            totals.hbm_bytes += sum(
                _aval_bytes(v.aval) for v in eqn.invars
            ) + sum(_aval_bytes(v.aval) for v in eqn.outvars)
            self._check_forbidden(eqn, name)
        return totals

    def _check_forbidden(self, eqn, name: str, container: bool = False) -> None:
        if name in CALLBACK_PRIMS and self.callbacks_forbidden:
            self.violations.append(
                Violation(
                    "callback", name, _loc(eqn),
                    "host callback inside a step region — one host round "
                    "trip per step",
                )
            )
        elif name in TRANSFER_PRIMS:
            self.violations.append(
                Violation(
                    "transfer", name, _loc(eqn),
                    "in-graph host transfer inside a step region",
                )
            )
        elif name == "gather":
            operand = _aval_bytes(eqn.invars[0].aval)
            moved = sum(_aval_bytes(v.aval) for v in eqn.outvars)
            if (
                operand >= self.operand_budget
                and moved >= self.gather_frac * operand
            ):
                self.violations.append(
                    Violation(
                        "full-carry-gather", name, _loc(eqn),
                        f"gather moves {moved} B of a {operand} B operand "
                        f"(>= {self.gather_frac:.0%}) — the r8 regression "
                        "class; gather a bounded window instead",
                    )
                )
        if container:
            return
        for v in eqn.outvars:
            dtype = getattr(v.aval, "dtype", None)
            if dtype is not None and str(dtype) == "float64":
                self.violations.append(
                    Violation(
                        "f64", name, _loc(eqn),
                        "float64 intermediate — the engines are u32-native; "
                        "an f64 is an accidental promotion "
                        "(check jax_enable_x64 and python-float literals)",
                    )
                )


def audit_fn(
    fn,
    args: tuple,
    *,
    name: str = "step",
    kwargs: Optional[dict] = None,
    host_slots: tuple = (),
    step_mode: str = "loop",
    operand_budget: int = 1 << 20,
    gather_frac: float = 0.75,
    callbacks_forbidden: bool = True,
) -> AuditReport:
    """Abstractly trace `fn(*args)` (ShapeDtypeStruct operands — no device
    work) and audit the jaxpr. `host_slots` are indices into `args` the
    host re-uploads every dispatch (the per-step PCIe floor reported as
    `transfer_bytes`). `step_mode` picks what `report.step` means:
    "loop" (chunked engines — the largest while body IS one search step)
    or "total" (per-batch kernels like the frontier step, whose only
    internal while is the insert chain-overflow loop).
    `operand_budget`/`gather_frac` tune the full-carry gather rule (see
    module docstring)."""
    if step_mode not in ("loop", "total"):
        raise ValueError(f"step_mode must be 'loop' or 'total', got {step_mode!r}")
    jaxpr = jax.make_jaxpr(fn)(*args, **(kwargs or {}))
    walker = _Walker(
        operand_budget=operand_budget,
        gather_frac=gather_frac,
        callbacks_forbidden=callbacks_forbidden,
    )
    totals = walker.walk(jaxpr)
    step = (
        max(walker.while_bodies, key=lambda t: t.hbm_bytes, default=totals)
        if step_mode == "loop"
        else totals
    )
    flat_in, _ = jax.tree.flatten((args, kwargs or {}))
    in_bytes = sum(_aval_bytes(a) for a in flat_in)
    out_bytes = sum(_aval_bytes(v.aval) for v in jaxpr.jaxpr.outvars)
    host = jax.tree.flatten(tuple(args[i] for i in host_slots))[0]
    return AuditReport(
        name=name,
        totals=totals,
        step=step,
        violations=walker.violations,
        in_bytes=in_bytes,
        out_bytes=out_bytes,
        transfer_bytes=sum(_aval_bytes(a) for a in host),
    )
