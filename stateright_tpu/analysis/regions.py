"""Step-region inference: which project functions run TRACED (inside `jit`
or a `lax.while_loop`/`scan` body) — the scope of srlint's no-host-sync rule.

The repo's invariant (SURVEY §7, ROADMAP r8 notes) is prose today: "nothing
host-syncs mid-loop". This module makes it mechanical. A function is a
**step-region root** when any of these hold:

- it is decorated with ``@jax.jit`` or ``@partial(jax.jit, ...)``;
- it is passed through ``jax.jit(f)`` / ``jax.vmap(f)`` / ``shard_map(f,
  ...)`` anywhere in its module (including nests like
  ``jax.jit(jax.vmap(f))`` and re-binding assignments ``f = jax.jit(f)``);
- it is passed as a function argument to ``jax.lax.while_loop`` /
  ``fori_loop`` / ``scan`` / ``cond`` / ``switch`` (lambda arguments count:
  calls made inside such a lambda are attributed to the lambda's enclosing
  function, which is how the engines' ``lambda c: body(c, ...)`` loop
  wrappers are followed);
- its ``def`` line (or the line above) carries a ``# srlint: step-region``
  marker — the explicit annotation for functions reached only through
  data-driven dispatch the static pass cannot see (e.g. the hash-table
  insert implementations selected from an ``INSERT_VARIANTS`` dict).

The full region is the transitive closure of the project call graph from
those roots. Resolution is deliberately best-effort and *under*-approximate
where precision is impossible (dynamic dispatch), with two recall helpers:

- default-argument edges: ``def expand_insert(..., insert=_insert_impl)``
  adds an edge to ``_insert_impl`` (the callee is invoked through the
  parameter);
- duck edges: an attribute call ``x.expand(...)`` links to every project
  *method* named ``expand`` unless the name is a common container/stdlib
  verb (``append``, ``get``, ...) — this is what pulls the tensor models'
  ``expand``/``within_boundary`` kernels into the region.
"""

from __future__ import annotations

import ast
import io
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

#: jax entry points whose function-valued arguments run traced.
TRACED_HOFS = {
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.associative_scan",
    "jax.vmap",
    "jax.pmap",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.checkpoint",
    "jax.remat",
}

#: wrappers where wrapper(f) means f runs traced when the result is called.
TRACED_WRAPPERS = {"jax.jit", "jax.pmap", "jax.vmap"} | TRACED_HOFS

#: attribute-call names NEVER duck-resolved (common container/stdlib verbs
#: that would otherwise alias project methods and flood the region).
DUCK_DENYLIST = {
    "append", "appendleft", "add", "get", "items", "keys", "values", "pop",
    "popleft", "close", "update", "join", "run", "read", "write", "clear",
    "copy", "extend", "sum", "mean", "max", "min", "any", "all", "reshape",
    "astype", "set", "split", "strip", "encode", "decode", "format",
    "register", "fresh", "stats", "metrics", "summary", "drain", "put",
    "insert", "search", "checkpoint", "flat", "tobytes", "item",
}

STEP_REGION_MARKER = "step-region"


@dataclass
class FuncInfo:
    module: str  # dotted module name
    qualname: str  # e.g. "FrontierSearch._build_step.step"
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    cls: Optional[str]  # enclosing class name, if a method
    calls: set = field(default_factory=set)  # resolved callee ids
    duck_calls: set = field(default_factory=set)  # bare attr-call names
    is_root: bool = False
    root_reason: str = ""


@dataclass
class ModuleIndex:
    module: str
    path: Path
    tree: ast.Module
    source: str
    comments: dict  # line -> (comment text after "#", standalone?)
    import_map: dict  # local alias -> dotted target
    funcs: dict  # qualname -> FuncInfo


@dataclass
class Project:
    modules: dict  # dotted module name -> ModuleIndex
    methods_by_name: dict  # bare method name -> [(module, qualname)]

    def func(self, module: str, qualname: str) -> Optional[FuncInfo]:
        m = self.modules.get(module)
        return m.funcs.get(qualname) if m else None


def _comments_of(source: str) -> dict:
    out: dict = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                standalone = not tok.line[: tok.start[1]].strip()
                out[tok.start[0]] = (
                    tok.string.lstrip("#").strip(), standalone,
                )
    except tokenize.TokenError:  # pragma: no cover — ast.parse passed
        pass
    return out


def srlint_tokens(comments: dict, line: int) -> list:
    """`srlint:` directives attached to `line`: its own trailing comment
    plus a STANDALONE comment on the line directly above. A trailing
    comment on the previous code line annotates that line only — otherwise
    one annotation would silently allowlist its neighbour below. Returns
    the raw directive strings (text after "srlint:")."""
    out = []
    for ln, need_standalone in ((line, False), (line - 1, True)):
        c, standalone = comments.get(ln, ("", False))
        if c.startswith("srlint:") and (standalone or not need_standalone):
            out.append(c[len("srlint:"):].strip())
    return out


def module_name_for(path: Path, root: Path) -> str:
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return path.stem
    parts = list(rel.with_suffix("").parts)
    if parts[-1] == "__init__":
        parts = parts[:-1] or [rel.parts[0]]
    return ".".join(parts)


def _build_import_map(
    tree: ast.Module, module: str, is_pkg: bool = False,
) -> dict:
    """alias -> dotted target for every import in the module (including
    function-local imports — the engines import store helpers inside
    builder functions)."""
    out: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                full = module.split(".")
                # In a package __init__ the dotted name (with "__init__"
                # already stripped) names the package itself, so level 1
                # means "this package", not the parent.
                strip = node.level - 1 if is_pkg else node.level
                base = full[: len(full) - strip] if strip else full
                prefix = ".".join(
                    base + ([node.module] if node.module else [])
                )
            else:
                prefix = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = (
                    f"{prefix}.{a.name}" if prefix else a.name
                )
    return out


def _dotted(node: ast.AST, import_map: dict) -> Optional[str]:
    """Best-effort dotted name of an expression ('jax.lax.while_loop'),
    resolving the leading alias through the import map."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(import_map.get(node.id, node.id))
    return ".".join(reversed(parts))


def _own_defs(stmts) -> Iterator:
    """FunctionDefs belonging directly to this body: top-level defs plus
    defs nested in non-def statements (if/try/with), but NOT defs inside
    other defs (those belong to the inner scope)."""
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield st
        elif isinstance(st, ast.ClassDef):
            continue  # handled as a class scope by the caller
        else:
            stack = [st]
            while stack:
                n = stack.pop()
                for child in ast.iter_child_nodes(n):
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        yield child
                    elif not isinstance(child, ast.ClassDef):
                        stack.append(child)


def _walk_stop_at_defs(node: ast.AST) -> Iterator:
    """Yield descendants of `node`, not descending into nested function
    defs (lambdas ARE descended — their calls belong to the enclosing
    function)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


class _Collector:
    def __init__(self, mi: ModuleIndex):
        self.mi = mi

    def process(self) -> None:
        self._body(self.mi.tree.body, scopes=[], cls=None)

    def _body(self, stmts, scopes, cls) -> None:
        for node in _own_defs(stmts):
            self._func(node, scopes, cls)
        for st in stmts:
            if isinstance(st, ast.ClassDef):
                self._body(st.body, scopes, st.name)

    def _func(self, node, scopes, cls) -> None:
        prefix = ([cls] if cls else []) + scopes
        qual = ".".join(prefix + [node.name])
        fi = FuncInfo(self.mi.module, qual, node, cls)
        self.mi.funcs[qual] = fi
        # `# srlint: step-region` marker on/above the def line.
        for d in srlint_tokens(self.mi.comments, node.lineno):
            if d.split()[:1] == [STEP_REGION_MARKER]:
                fi.is_root = True
                fi.root_reason = "marker"
        # Decorators: @jax.jit / @partial(jax.jit, ...).
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            dn = _dotted(target, self.mi.import_map)
            if dn in TRACED_WRAPPERS:
                fi.is_root = True
                fi.root_reason = fi.root_reason or dn
            elif (
                isinstance(dec, ast.Call)
                and dn in ("functools.partial", "partial")
                and dec.args
            ):
                inner = _dotted(dec.args[0], self.mi.import_map)
                if inner in TRACED_WRAPPERS:
                    fi.is_root = True
                    fi.root_reason = fi.root_reason or inner
        # Default-argument edges (callee invoked through the parameter).
        args = node.args
        for default in list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]:
            if isinstance(default, ast.Name):
                fi.calls.add(self._resolve_name(default.id, scopes, cls))
        # Calls in this function's own statements (stopping at nested defs).
        inner_scopes = prefix + [node.name]
        for st in node.body:
            for sub in _walk_stop_at_defs(st):
                if isinstance(sub, ast.Call):
                    self._record_call(sub, fi, inner_scopes, cls)
        # Recurse into nested defs (not methods — cls does not propagate).
        self._body(node.body, inner_scopes, None)

    def _resolve_name(self, name, scopes, cls) -> str:
        # Innermost enclosing scope that defines `name` as a def wins.
        for i in range(len(scopes), -1, -1):
            qual = ".".join(scopes[:i] + [name])
            if qual in self.mi.funcs:
                return f"{self.mi.module}:{qual}"
        if cls and f"{cls}.{name}" in self.mi.funcs:
            return f"{self.mi.module}:{cls}.{name}"
        target = self.mi.import_map.get(name)
        if target:
            return target
        return f"{self.mi.module}:{name}"

    def _record_call(self, call, fi, scopes, cls) -> None:
        f = call.func
        if isinstance(f, ast.Name):
            fi.calls.add(self._resolve_name(f.id, scopes, cls))
        elif isinstance(f, ast.Attribute):
            if (
                isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls")
                and fi.cls
            ):
                fi.calls.add(f"{self.mi.module}:{fi.cls}.{f.attr}")
            else:
                dn = _dotted(f, self.mi.import_map)
                if dn:
                    fi.calls.add(dn)
                if f.attr not in DUCK_DENYLIST:
                    fi.duck_calls.add(f.attr)


def _scan_traced_uses(mi: ModuleIndex) -> None:
    """Mark functions passed through jit/vmap/shard_map/while_loop-style
    call sites anywhere in the module (re-binding assignments included)."""

    by_name: dict = {}
    for fi in mi.funcs.values():
        by_name.setdefault(fi.node.name, []).append(fi)

    def mark(arg, reason) -> None:
        if isinstance(arg, ast.Name):
            for fi in by_name.get(arg.id, ()):
                fi.is_root = True
                fi.root_reason = fi.root_reason or reason
        elif isinstance(arg, ast.Call):
            dn = _dotted(arg.func, mi.import_map)
            if dn in TRACED_WRAPPERS:
                for a in arg.args:
                    mark(a, dn)

    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        dn = _dotted(node.func, mi.import_map)
        if dn in TRACED_WRAPPERS and dn not in TRACED_HOFS:
            # jax.jit(f) / jax.jit(jax.vmap(f))
            for arg in node.args[:1]:
                mark(arg, dn)
        elif dn in TRACED_HOFS:
            for arg in node.args:
                mark(arg, dn)
        elif dn in ("functools.partial", "partial") and node.args:
            inner = _dotted(node.args[0], mi.import_map)
            if inner in TRACED_WRAPPERS:
                for arg in node.args[1:]:
                    mark(arg, inner)
        elif isinstance(node.func, ast.Call):
            # partial(jax.jit, ...)(chunk_k)
            inner_dn = _dotted(node.func.func, mi.import_map)
            if (
                inner_dn in ("functools.partial", "partial")
                and node.func.args
            ):
                wrapped = _dotted(node.func.args[0], mi.import_map)
                if wrapped in TRACED_WRAPPERS:
                    for arg in node.args:
                        mark(arg, wrapped)


def build_project(paths: list, root: Path) -> Project:
    modules: dict = {}
    for path in paths:
        path = Path(path)
        try:
            source = path.read_text()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            continue
        module = module_name_for(path, root)
        mi = ModuleIndex(
            module=module,
            path=path,
            tree=tree,
            source=source,
            comments=_comments_of(source),
            import_map=_build_import_map(
                tree, module, is_pkg=path.name == "__init__.py",
            ),
            funcs={},
        )
        _Collector(mi).process()
        _scan_traced_uses(mi)
        modules[module] = mi

    methods_by_name: dict = {}
    for mi in modules.values():
        for qual, fi in mi.funcs.items():
            if fi.cls is not None:
                methods_by_name.setdefault(fi.node.name, []).append(
                    (mi.module, qual)
                )
    return Project(modules=modules, methods_by_name=methods_by_name)


def step_region(project: Project) -> set:
    """The set of (module, qualname) pairs reachable from step-region
    roots through the project call graph."""
    region: set = set()
    work = [
        (mi.module, qual)
        for mi in project.modules.values()
        for qual, fi in mi.funcs.items()
        if fi.is_root
    ]

    def resolve(callee: str) -> list:
        out = []
        if ":" in callee:  # module-local form "pkg.mod:Qual.name"
            mod, qual = callee.split(":", 1)
            mi = project.modules.get(mod)
            if mi and qual in mi.funcs:
                out.append((mod, qual))
            return out
        # Dotted import form "stateright_tpu.tensor.frontier.expand_insert"
        parts = callee.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            mi = project.modules.get(mod)
            if mi is None:
                continue
            qual = ".".join(parts[cut:])
            if qual in mi.funcs:
                out.append((mod, qual))
            else:
                tail = parts[-1]
                out.extend(
                    (mod, q)
                    for q, fi in mi.funcs.items()
                    if fi.node.name == tail and fi.cls is None
                )
            break
        return out

    while work:
        key = work.pop()
        if key in region:
            continue
        region.add(key)
        fi = project.func(*key)
        if fi is None:
            continue
        for callee in fi.calls:
            work.extend(c for c in resolve(callee) if c not in region)
        for duck in fi.duck_calls:
            work.extend(
                c
                for c in project.methods_by_name.get(duck, ())
                if c not in region
            )
    return region
