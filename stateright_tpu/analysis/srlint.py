"""srlint: project-specific AST lint rules for the invariants this repo
states in prose (and has repeatedly paid for re-breaking).

Rules (allowlist token in parentheses — `# srlint: <token> <reason>` on the
offending line or the line above; a token without a reason is itself an
error):

- **SR001 host-sync-in-step-region** (`host-ok`): no host materialization —
  ``.item()``, ``float(...)``, ``bool(...)``, ``np.asarray``/``np.array``,
  ``jax.device_get``, ``.block_until_ready()`` — reachable from a traced
  step region (see regions.py). The r8 regression class: one stray sync in
  a while_loop body turns a fused device step into a per-step PCIe round
  trip.
- **SR002 bare-checkpoint-write** (`ckpt-ok`): checkpoint-shaped writes —
  ``np.savez``/``np.savez_compressed``, ``open(..., "wb")``, a bare
  ``atomic_savez``, or a bare BLOB write (``put_blob`` or a ``.put``/
  ``.put_if_absent`` on a blob-shaped receiver) — anywhere outside
  ``faults/ckptio.py``, the blob backend (``faults/blobstore.py``), or
  the lease module (``service/lease.py``). r10 found every checkpoint
  writer torn; the atomic CRC writer is the only sanctioned path — and
  since the epoch-fence PR, `ckptio.fenced_savez` is the only sanctioned
  CALLER of it: a write that skips the wrapper also skips the lease
  stamp + the write-side revocation check, which is exactly the
  zombie-writer hole. A bare blob ``put`` skips the CRC footer AND the
  fence, so it gets the same verdict.
- **SR003 undeclared-detail-key** (`key-ok`): every string-literal
  ``detail[...]`` subscript, every ``REGISTRY.register("<source>")``, and
  every flight-recorder ``events.emit("<type>", ...)`` (any receiver named
  ``events``/``_events``/``journal``/``_journal``) must use a key declared
  in ``obs/schema.py`` (DETAIL_KEYS + sub-schemas + REGISTRY_SOURCES +
  EVENT_TYPES) — journal event names are a cross-replica forensic
  contract exactly like the counter vocabulary.
- **SR004 unguarded-failure-surface** (`fault-ok`): a
  ``raise RuntimeError/OSError`` in engine/store/service code must sit in a
  function that also calls ``maybe_fault()`` (i.e. the failure surface is
  on the chaos plane) or carry an annotation saying why not.
- **SR005 knob-literal-drift** (`knob-ok`): engine-knob string literals
  (``insert_variant``/``store``/``table_layout``/``append``/``engine``)
  compared, defaulted, or passed as keywords must be members of the one
  registry (``stateright_tpu/knobs.py``); restating a knob universe as a
  literal tuple is flagged even when its members are currently correct.

The pass is file-local plus the project call graph from regions.py; it
imports ``obs/schema.py`` and ``knobs.py`` BY PATH (no package import), so
linting never drags jax in.
"""

from __future__ import annotations

import ast
import importlib.util
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from .regions import (
    ModuleIndex,
    Project,
    _dotted,
    _walk_stop_at_defs,
    build_project,
    srlint_tokens,
    step_region,
)

#: allowlist tokens per rule (+ the region marker handled in regions.py).
RULE_TOKENS = {
    "SR001": "host-ok",
    "SR002": "ckpt-ok",
    "SR003": "key-ok",
    "SR004": "fault-ok",
    "SR005": "knob-ok",
}
KNOWN_TOKENS = set(RULE_TOKENS.values()) | {"step-region"}

#: name-call host materializers (resolved through the import map).
HOST_NAME_CALLS = {"float", "bool"}
HOST_DOTTED_CALLS = {
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
    "jax.block_until_ready",
}
HOST_ATTR_CALLS = {"item", "block_until_ready"}

CKPT_WRITERS = {"numpy.save", "numpy.savez", "numpy.savez_compressed"}
#: Callables only the blessed modules may invoke directly: everyone else
#: goes through `ckptio.fenced_savez`, the seam that carries the epoch
#: fence (stamp + write-side revocation check).
CKPT_RAW_ATOMIC = {
    "atomic_savez",
    "ckptio.atomic_savez",
    "stateright_tpu.faults.ckptio.atomic_savez",
}
CKPT_MODULE_SUFFIX = "faults.ckptio"
#: Modules sanctioned to do raw checkpoint-shaped I/O: the atomic CRC
#: writer itself, the blob backend it routes through, and the lease store
#: (its CRC'd lease records follow the same tmp/fsync/rename discipline
#: but are not npz).
CKPT_MODULE_SUFFIXES = ("faults.ckptio", "faults.blobstore", "service.lease")

#: The blob-store write surface: the URI-level helper by (resolved)
#: dotted name, plus `.put`/`.put_if_absent` method calls on blob-shaped
#: receivers (a name or attribute mentioning "blob" — `blob.put`,
#: `self._blobstore.put`; CACHE.put/queue.put stay out of scope). Only
#: `ckptio.fenced_savez`/`write_record` may write blobs: a bare put skips
#: the CRC footer and the epoch fence.
BLOB_WRITE_CALLS = {
    "put_blob",
    "blobstore.put_blob",
    "stateright_tpu.faults.blobstore.put_blob",
}
BLOB_PUT_METHODS = {"put", "put_if_absent", "put_fenced"}

#: module prefixes whose failure surfaces must be on the chaos plane.
FAULT_SCOPE = (
    "stateright_tpu.tensor.frontier",
    "stateright_tpu.tensor.resident",
    "stateright_tpu.parallel.sharded",
    "stateright_tpu.store",
    "stateright_tpu.service",
    # The blob-store backends' failure surfaces (retry exhaustion, HTTP
    # translation) must sit on the chaos plane like every other store's —
    # the prefix match covers blobstore_s3/blobstore_gcs too.
    "stateright_tpu.faults.blobstore",
    # The managed-store credential chain: a chain-exhausted resolve is a
    # failure surface exactly like retry exhaustion (creds.refresh is its
    # chaos point).
    "stateright_tpu.faults.creds",
)
FAULT_EXC_NAMES = {
    "RuntimeError", "OSError", "IOError", "BlobUnavailable",
    "CredentialError",
}

#: knob parameter/variable names -> registry attribute (knobs.py).
KNOB_UNIVERSES = {
    "insert_variant": "INSERT_VARIANTS",
    "store": "STORE_KINDS",
    "table_layout": "TABLE_LAYOUTS",
    "append": "APPEND_KINDS",
    "engine": "ENGINES",
    # knobs.CHECKER_MODES (spawn_tpu's `mode=`) is deliberately NOT mapped:
    # "mode" is a ubiquitous stdlib/jnp keyword (open(mode="w"),
    # put_along_axis(mode="drop")), so literal-linting it drowns in false
    # positives; the builder validates against the registry tuple instead.
    "dedup": "SIM_DEDUP_KINDS",
    # Blob-store backend selectors: the smoke's `--backend`, the URI
    # dispatcher's return, the bench per-backend legs. A literal outside
    # ("file", "blob", "s3", "gs") — e.g. a scheme string compared
    # against `backend` — is exactly the drift the r24 dispatcher
    # generalization must bound.
    "backend": "BLOB_BACKENDS",
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _load_by_path(py_path: Path, name: str):
    spec = importlib.util.spec_from_file_location(name, py_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _default_paths(root: Path) -> list:
    paths = sorted((root / "stateright_tpu").rglob("*.py"))
    for extra in ("bench.py", "__graft_entry__.py"):
        p = root / extra
        if p.exists():
            paths.append(p)
    scripts = root / "scripts"
    if scripts.is_dir():
        paths.extend(sorted(scripts.glob("*.py")))
    return [p for p in paths if "__pycache__" not in p.parts]


class Linter:
    def __init__(self, project: Project, root: Path, schema=None, knobs=None):
        self.project = project
        self.root = root
        pkg = root / "stateright_tpu"
        self.schema = schema or _load_by_path(
            pkg / "obs" / "schema.py", "_srlint_schema"
        )
        self.knobs = knobs or _load_by_path(
            pkg / "knobs.py", "_srlint_knobs"
        )
        self.region = step_region(project)
        self.findings: list = []
        self._detail_paths = self.schema.all_detail_key_paths()
        self._detail_subs = {s for s, _ in self.schema.DETAIL_SUBSCHEMAS}
        self._event_types = getattr(self.schema, "EVENT_TYPES", {}) or {}

    # -- helpers ---------------------------------------------------------------

    def _allowed(self, mi: ModuleIndex, line: int, rule: str) -> bool:
        token = RULE_TOKENS[rule]
        return any(
            d.split()[:1] == [token] for d in srlint_tokens(mi.comments, line)
        )

    def _emit(self, mi: ModuleIndex, node, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if self._allowed(mi, line, rule):
            return
        self.findings.append(
            Finding(rule, str(mi.path.relative_to(self.root)), line, message)
        )

    # -- SR000: directive hygiene ----------------------------------------------

    def _check_directives(self, mi: ModuleIndex) -> None:
        for line, (text, _standalone) in mi.comments.items():
            if not text.startswith("srlint:"):
                continue
            directive = text[len("srlint:"):].strip()
            words = directive.split()
            if not words or words[0] not in KNOWN_TOKENS:
                self.findings.append(
                    Finding(
                        "SR000",
                        str(mi.path.relative_to(self.root)),
                        line,
                        f"unknown srlint directive {directive!r} "
                        f"(known: {sorted(KNOWN_TOKENS)})",
                    )
                )
            elif words[0] != "step-region" and len(words) < 2:
                self.findings.append(
                    Finding(
                        "SR000",
                        str(mi.path.relative_to(self.root)),
                        line,
                        f"srlint allowlist '{words[0]}' needs a reason "
                        "(e.g. '# srlint: host-ok chunk boundary, already "
                        "synced')",
                    )
                )

    # -- SR001: host sync inside a step region ---------------------------------

    def _host_sync_kind(self, call: ast.Call, mi: ModuleIndex) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            resolved = mi.import_map.get(f.id, f.id)
            if resolved in HOST_NAME_CALLS:
                return f"{f.id}()"
            if resolved in HOST_DOTTED_CALLS:
                return resolved
        elif isinstance(f, ast.Attribute):
            dn = _dotted(f, mi.import_map)
            if dn in HOST_DOTTED_CALLS:
                return dn
            if f.attr in HOST_ATTR_CALLS:
                return f".{f.attr}()"
        return None

    def _check_host_sync(self, mi: ModuleIndex) -> None:
        for qual, fi in mi.funcs.items():
            if (mi.module, qual) not in self.region:
                continue
            for st in fi.node.body:
                for sub in _walk_stop_at_defs(st):
                    if not isinstance(sub, ast.Call):
                        continue
                    kind = self._host_sync_kind(sub, mi)
                    if kind:
                        self._emit(
                            mi,
                            sub,
                            "SR001",
                            f"host materialization {kind} inside step "
                            f"region {mi.module}:{qual} (traced code must "
                            "stay on device; annotate '# srlint: host-ok "
                            "<reason>' if this runs at trace time only)",
                        )

    # -- SR002: checkpoint writes outside ckptio -------------------------------

    def _check_ckpt_writes(self, mi: ModuleIndex) -> None:
        if mi.module.endswith(CKPT_MODULE_SUFFIXES):
            return
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = (
                _dotted(node.func, mi.import_map)
                if isinstance(node.func, (ast.Attribute, ast.Name))
                else None
            )
            if dn in CKPT_WRITERS:
                self._emit(
                    mi,
                    node,
                    "SR002",
                    f"bare {dn} — checkpoint writes must go through "
                    "faults/ckptio.py (atomic tmp+fsync+rename with CRC "
                    "footer)",
                )
            elif dn in CKPT_RAW_ATOMIC:
                self._emit(
                    mi,
                    node,
                    "SR002",
                    f"bare {dn} outside faults/ckptio.py / service/"
                    "lease.py — use ckptio.fenced_savez (the seam that "
                    "carries the lease stamp + write-side revocation "
                    "check; lease=None degrades to the plain atomic "
                    "writer)",
                )
            elif dn in BLOB_WRITE_CALLS or self._blob_put(node):
                self._emit(
                    mi,
                    node,
                    "SR002",
                    "bare blob-store write outside faults/ckptio.py / "
                    "faults/blobstore.py — route it through "
                    "ckptio.fenced_savez / write_record (the seam that "
                    "carries the CRC footer and the epoch fence)",
                )
            elif (
                dn in ("open", "io.open")
                or (isinstance(node.func, ast.Name) and node.func.id == "open")
                or (
                    # Any receiver's .open() — Path(...).open("wb") is the
                    # same torn-write class as the open() builtin.
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "open"
                )
            ):
                mode = None
                # The builtin/io/gzip open take mode second; Path.open takes
                # it first. Accept a mode-shaped string constant in either
                # slot (a path constant like "raw.bin" must not pass for a
                # mode even though it contains 'w' and 'b').
                for a in node.args[:2]:
                    if (
                        isinstance(a, ast.Constant)
                        and isinstance(a.value, str)
                        and 0 < len(a.value) <= 4
                        and set(a.value) <= set("rwxab+tU")
                    ):
                        mode = a.value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                        mode = kw.value.value
                if isinstance(mode, str) and "b" in mode and (
                    "w" in mode or "a" in mode or "+" in mode
                ):
                    self._emit(
                        mi,
                        node,
                        "SR002",
                        f"binary write open(..., {mode!r}) outside "
                        "faults/ckptio.py — persistent state must use the "
                        "atomic checkpoint writer",
                    )

    @staticmethod
    def _blob_put(node: ast.Call) -> bool:
        """True for `.put`/`.put_if_absent` method calls whose receiver is
        blob-shaped (a name/attribute mentioning "blob") — the BlobStore
        write surface, without dragging CACHE.put/queue.put into scope."""
        f = node.func
        if not (
            isinstance(f, ast.Attribute) and f.attr in BLOB_PUT_METHODS
        ):
            return False
        recv = f.value
        if isinstance(recv, ast.Name):
            return "blob" in recv.id.lower()
        if isinstance(recv, ast.Attribute):
            return "blob" in recv.attr.lower()
        if isinstance(recv, ast.Call):
            # blob_backend(root).put(...) — the factory names the surface.
            cf = recv.func
            name = (
                cf.id if isinstance(cf, ast.Name)
                else cf.attr if isinstance(cf, ast.Attribute) else ""
            )
            return "blob" in name.lower()
        return False

    # -- SR003: undeclared detail / registry keys ------------------------------

    @staticmethod
    def _events_receiver(node: ast.expr) -> bool:
        """True when a call receiver is journal-shaped — `events.emit`,
        `self._events.emit`, `plan.events.emit`, `journal.emit` — so the
        emit vocabulary check doesn't fire on unrelated emit() methods."""
        names = {"events", "_events", "journal", "_journal"}
        if isinstance(node, ast.Name):
            return node.id in names
        if isinstance(node, ast.Attribute):
            return node.attr in names
        return False

    def _detail_base(self, node: ast.expr) -> Optional[str]:
        """'' for `detail[...]`/`x.detail[...]`, the sub-dict name for
        `detail["service"][...]` chains, None when not detail-shaped."""
        if isinstance(node, ast.Name) and node.id == "detail":
            return ""
        if isinstance(node, ast.Attribute) and node.attr == "detail":
            return ""
        if isinstance(node, ast.Subscript):
            inner = self._detail_base(node.value)
            if inner == "" and isinstance(node.slice, ast.Constant):
                key = node.slice.value
                if key in self._detail_subs:
                    return key
        return None

    def _check_detail_keys(self, mi: ModuleIndex) -> None:
        lib_module = mi.module.startswith("stateright_tpu")
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Subscript) and isinstance(
                node.slice, ast.Constant
            ):
                key = node.slice.value
                if not isinstance(key, str):
                    continue
                base = self._detail_base(node.value)
                if base is None:
                    continue
                if not lib_module and isinstance(node.value, ast.Name):
                    # scripts/bench may keep their own local `detail` dicts;
                    # only attribute subscripts (`result.detail[...]`) bind
                    # them to the schema outside the library.
                    continue
                path = f"{base}.{key}" if base else key
                if path not in self._detail_paths:
                    self._emit(
                        mi,
                        node,
                        "SR003",
                        f"detail key {path!r} is not declared in "
                        "obs/schema.py — add it to the schema (with owner "
                        "and meaning) before producing/consuming it",
                    )
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "register"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "REGISTRY"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                ):
                    src = node.args[0].value
                    if src not in self.schema.REGISTRY_SOURCES:
                        self._emit(
                            mi,
                            node,
                            "SR003",
                            f"REGISTRY source {src!r} is not declared in "
                            "obs/schema.py REGISTRY_SOURCES",
                        )
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "emit"
                    and self._events_receiver(f.value)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    etype = node.args[0].value
                    if etype not in self._event_types:
                        self._emit(
                            mi,
                            node,
                            "SR003",
                            f"journal event type {etype!r} is not declared "
                            "in obs/schema.py EVENT_TYPES — pin the "
                            "vocabulary (name + required fields) before "
                            "emitting it",
                        )

    # -- SR004: failure surfaces off the chaos plane ---------------------------

    def _check_fault_surfaces(self, mi: ModuleIndex) -> None:
        if not mi.module.startswith(FAULT_SCOPE):
            return
        for qual, fi in mi.funcs.items():
            has_boundary = any(
                isinstance(sub, ast.Call)
                and (
                    (
                        isinstance(sub.func, ast.Name)
                        and sub.func.id == "maybe_fault"
                    )
                    or (
                        isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "maybe_fault"
                    )
                )
                for st in fi.node.body
                for sub in _walk_stop_at_defs(st)
            )
            if has_boundary:
                continue
            for st in fi.node.body:
                for sub in _walk_stop_at_defs(st):
                    if not isinstance(sub, ast.Raise) or sub.exc is None:
                        continue
                    exc = sub.exc
                    name = None
                    if isinstance(exc, ast.Call) and isinstance(
                        exc.func, ast.Name
                    ):
                        name = exc.func.id
                    elif isinstance(exc, ast.Name):
                        name = exc.id
                    if name in FAULT_EXC_NAMES:
                        self._emit(
                            mi,
                            sub,
                            "SR004",
                            f"raise {name} in {mi.module}:{qual} without a "
                            "maybe_fault() boundary in the same function — "
                            "put the surface on the chaos plane or annotate "
                            "'# srlint: fault-ok <reason>'",
                        )

    # -- SR005: knob literals off the registry ---------------------------------

    def _knob_name(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id if node.id in KNOB_UNIVERSES else None
        if isinstance(node, ast.Attribute):
            return node.attr if node.attr in KNOB_UNIVERSES else None
        if isinstance(node, ast.Call):  # engine_kwargs.get("store")
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in KNOB_UNIVERSES
            ):
                return node.args[0].value
        if isinstance(node, ast.Subscript) and isinstance(
            node.slice, ast.Constant
        ):
            if node.slice.value in KNOB_UNIVERSES:
                return node.slice.value
        return None

    def _universe(self, knob: str) -> tuple:
        return getattr(self.knobs, KNOB_UNIVERSES[knob])

    def _check_knob_value(self, mi, node, knob: str, value) -> None:
        if isinstance(value, str) and value not in self._universe(knob):
            self._emit(
                mi,
                node,
                "SR005",
                f"{knob} literal {value!r} is not in "
                f"knobs.{KNOB_UNIVERSES[knob]} {self._universe(knob)}",
            )

    def _check_knob_literals(self, mi: ModuleIndex) -> None:
        if not mi.module.startswith("stateright_tpu"):
            return
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Compare):
                knob = self._knob_name(node.left)
                if knob is None and len(node.comparators) == 1:
                    knob = self._knob_name(node.comparators[0])
                    others = [node.left]
                else:
                    others = node.comparators
                if knob is None:
                    continue
                for op, comp in zip(node.ops, others):
                    if isinstance(comp, ast.Constant):
                        self._check_knob_value(mi, node, knob, comp.value)
                    elif isinstance(op, (ast.In, ast.NotIn)) and isinstance(
                        comp, (ast.Tuple, ast.List, ast.Set)
                    ):
                        consts = [
                            e.value
                            for e in comp.elts
                            if isinstance(e, ast.Constant)
                        ]
                        if consts:
                            self._emit(
                                mi,
                                node,
                                "SR005",
                                f"{knob} universe restated as a literal "
                                f"{tuple(consts)!r} — membership tests must "
                                f"use knobs.{KNOB_UNIVERSES[knob]}",
                            )
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in KNOB_UNIVERSES and isinstance(
                        kw.value, ast.Constant
                    ):
                        self._check_knob_value(
                            mi, kw, kw.arg, kw.value.value
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                pos = a.posonlyargs + a.args
                for arg, default in zip(pos[len(pos) - len(a.defaults):],
                                        a.defaults):
                    if arg.arg in KNOB_UNIVERSES and isinstance(
                        default, ast.Constant
                    ):
                        self._check_knob_value(
                            mi, default, arg.arg, default.value
                        )
                for arg, default in zip(a.kwonlyargs, a.kw_defaults):
                    if (
                        default is not None
                        and arg.arg in KNOB_UNIVERSES
                        and isinstance(default, ast.Constant)
                    ):
                        self._check_knob_value(
                            mi, default, arg.arg, default.value
                        )

    # -- driver ----------------------------------------------------------------

    def run(self) -> list:
        for mi in self.project.modules.values():
            self._check_directives(mi)
            self._check_host_sync(mi)
            self._check_ckpt_writes(mi)
            self._check_detail_keys(mi)
            self._check_fault_surfaces(mi)
            self._check_knob_literals(mi)
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return self.findings


def lint_paths(
    paths: Optional[list] = None, root: Optional[Path] = None
) -> list:
    """Lint `paths` (default: the whole project) against the repo at
    `root`; returns sorted Findings."""
    root = Path(root) if root else Path(__file__).resolve().parents[2]
    paths = paths if paths is not None else _default_paths(root)
    project = build_project(paths, root)
    return Linter(project, root).run()


def lint_source(
    source: str,
    module: str = "fixture",
    root: Optional[Path] = None,
    schema=None,
    knobs=None,
) -> list:
    """Lint a single in-memory module (test fixtures). The module name
    controls scope-sensitive rules — name it e.g.
    'stateright_tpu.store.fixture' to put it in the fault scope."""
    import tempfile

    repo_root = Path(root) if root else Path(__file__).resolve().parents[2]
    pkg = repo_root / "stateright_tpu"
    schema = schema or _load_by_path(
        pkg / "obs" / "schema.py", "_srlint_schema"
    )
    knobs = knobs or _load_by_path(pkg / "knobs.py", "_srlint_knobs")
    with tempfile.TemporaryDirectory() as td:
        parts = module.split(".")
        p = Path(td, *parts[:-1])
        p.mkdir(parents=True, exist_ok=True)
        f = p / f"{parts[-1]}.py"
        f.write_text(source)
        project = build_project([f], Path(td))
        return Linter(project, Path(td), schema=schema, knobs=knobs).run()
