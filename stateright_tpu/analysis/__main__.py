"""``python -m stateright_tpu.analysis`` — the project's one static
analysis entry point.

Passes, in order (each independently skippable):

1. **srlint** (srlint.py): the five project lint rules over every repo
   .py file. Pure AST — jax is never imported.
2. **knob registry drift** (knobs.check_registry): imports the modules
   that re-state knob universes and reports disagreement with knobs.py.
   The imports pull in the engine spines (and so jax), which is why
   ``--skip-audit`` skips this pass too — on jax-free images srlint
   SR005 still covers knob-literal drift at the AST level.
3. **jaxpr audit** (anchors.py): abstract-trace each engine's step on the
   pinned 2pc-3 anchors, flag forbidden ops, and cross-check audited
   bytes against the costmodel. CPU-only and device-free, but it does
   import jax (seconds, not minutes).
4. **ruff / mypy** when the tools exist on PATH (config in
   pyproject.toml). The container this repo grew in does not ship them;
   they run wherever they are installed and are reported as "skipped
   (not installed)" otherwise — srlint is the gate that always runs.

Exit status 0 iff every pass that ran is clean. CI and
scripts/analysis_smoke.py call exactly this module.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[2]


def _run_srlint() -> int:
    from .srlint import lint_paths

    findings = lint_paths(root=ROOT)
    for f in findings:
        print(f)
    print(f"srlint: {len(findings)} finding(s)")
    return len(findings)


def _run_knob_drift() -> int:
    from ..knobs import check_registry

    problems = check_registry()
    for p in problems:
        print(f"knobs: {p}")
    print(f"knob registry: {len(problems)} drift(s)")
    return len(problems)


def _run_audit() -> int:
    from .anchors import MODEL_RATIO_MAX, MODEL_RATIO_MIN, audit_anchors

    bad = 0
    for name, ar in audit_anchors().items():
        if ar.skipped:
            print(f"audit {name}: skipped — {ar.skipped}")
            continue
        s = ar.report.summary()
        print(
            f"audit {name}: step {s['step_hbm_bytes']:,} B "
            f"/ {s['step_flops']:,} flop / {s['transfer_bytes']:,} B xfer; "
            f"model {ar.model_bytes:,.0f} B (ratio {ar.ratio:.1f})"
        )
        for v in ar.report.violations:
            print(f"audit {name}: {v}")
            bad += 1
        if not ar.ratio_ok:
            print(
                f"audit {name}: bytes ratio {ar.ratio:.1f} outside "
                f"[{MODEL_RATIO_MIN:g}, {MODEL_RATIO_MAX:g}] — the jaxpr "
                "and tensor/costmodel.py no longer describe the same program"
            )
            bad += 1
    return bad


def _run_tool(name: str, args: list) -> int:
    """ruff/mypy when installed; 0 findings when absent (reported)."""
    exe = shutil.which(name)
    if exe is None:
        print(f"{name}: skipped (not installed)")
        return 0
    proc = subprocess.run([exe, *args], cwd=ROOT)
    print(f"{name}: exit {proc.returncode}")
    # One problem per unclean tool, not the raw exit code: a signal-killed
    # tool returns a NEGATIVE code, which must not subtract from the
    # finding sum and cancel real findings into a clean exit.
    return 1 if proc.returncode != 0 else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m stateright_tpu.analysis",
        description="srlint + knob-drift + jaxpr audit (+ ruff/mypy)",
    )
    ap.add_argument(
        "--skip-audit", action="store_true",
        help="skip the jax-importing passes (jaxpr audit + cross-module "
             "knob drift); the remaining run is AST-only and sub-second",
    )
    ap.add_argument(
        "--skip-tools", action="store_true",
        help="skip ruff/mypy even when installed",
    )
    args = ap.parse_args(argv)

    # The sharded anchor needs 8 host devices on CPU; the flag only works
    # before jax initializes, which is why the audit pass imports lazily.
    if not args.skip_audit and "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    bad = _run_srlint()
    if not args.skip_audit:
        bad += _run_knob_drift()
        bad += _run_audit()
    if not args.skip_tools:
        bad += _run_tool("ruff", ["check", "."])
        bad += _run_tool("mypy", ["stateright_tpu"])
    print("analysis:", "clean" if bad == 0 else f"{bad} problem(s)")
    return 0 if bad == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
