"""Anchor configs for the jaxpr auditor: the exact engine builds whose
step programs are pinned as budgets.

One anchor per engine spine, all on the 2pc-3 model (the tier-1 parity
workload): small enough that abstract tracing takes seconds on CPU, big
enough that every step phase (expand, fingerprint, insert, append,
property masks) appears in the jaxpr. Shapes are pinned EXPLICITLY
(batch, table_log2, append variant) — budgets are meaningless if the
traced program floats with platform defaults.

`audit_anchors()` is the auditor's entry point: trace each anchor's step
kernel (`engine.audit_step()` — ShapeDtypeStructs only, no device
execution), audit the jaxpr (auditor.py), and cross-check the audited
per-step HBM bytes against the `tensor/costmodel.py` roofline prediction.
The jaxpr accounting is compiler-naive (every eqn materializes), so the
two will not match — but their RATIO is deterministic for a given
program, and a ratio outside [MODEL_RATIO_MIN, MODEL_RATIO_MAX] means one
side no longer describes the other: a giant new op the model does not
know, or a model term the program no longer runs.

The sharded anchor needs >= SHARDS devices
(``--xla_force_host_platform_device_count=8`` on CPU — conftest.py and
``python -m stateright_tpu.analysis`` both set it); it is skipped with a
note when the mesh cannot exist.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

#: the 2pc-3 anchor knobs, shared by tests/bench/CLI.
ANCHOR_MODEL = "2pc-3"
BATCH = 256
TABLE_LOG2 = 14
SHARDED_TABLE_LOG2 = 12  # per shard
SHARDS = 8
APPEND = "dus"  # pinned: CPU default is "scatter", budgets must not float

#: audited-vs-modeled per-step HBM byte ratio band (see module docstring).
MODEL_RATIO_MIN = 0.2
MODEL_RATIO_MAX = 50.0


@dataclass
class AnchorResult:
    report: object  # auditor.AuditReport
    model_bytes: float  # costmodel step_cost prediction
    ratio: float  # audited step bytes / model bytes
    ratio_ok: bool
    skipped: Optional[str] = None  # reason when the anchor could not build


def _model():
    from ..tensor.models import TensorTwoPhaseSys

    return TensorTwoPhaseSys(3)


def _model_bytes(model, table_log2: int, variant: str) -> float:
    from ..tensor import costmodel

    sc = costmodel.step_cost(
        model.lanes,
        model.max_actions,
        BATCH,
        table_log2,
        variant=costmodel.ENGINE_VARIANTS[("split", variant)],
        append=APPEND,
    )
    return sc.total_bytes


def _audit(engine, name: str, table_log2: int, variant: str, step_mode: str):
    from .auditor import audit_fn

    fn, args, host_slots = engine.audit_step()
    report = audit_fn(
        fn, args, name=name, host_slots=host_slots, step_mode=step_mode
    )
    mb = _model_bytes(engine.model, table_log2, variant)
    ratio = report.step.hbm_bytes / max(mb, 1.0)
    return AnchorResult(
        report=report,
        model_bytes=mb,
        ratio=ratio,
        ratio_ok=MODEL_RATIO_MIN <= ratio <= MODEL_RATIO_MAX,
    )


def audit_frontier() -> AnchorResult:
    from ..tensor.frontier import FrontierSearch

    eng = FrontierSearch(_model(), batch_size=BATCH, table_log2=TABLE_LOG2)
    return _audit(
        eng, f"frontier/{ANCHOR_MODEL}", TABLE_LOG2, "sort", "total"
    )


def audit_resident() -> AnchorResult:
    from ..tensor.resident import ResidentSearch

    eng = ResidentSearch(
        _model(), batch_size=BATCH, table_log2=TABLE_LOG2, append=APPEND
    )
    return _audit(eng, f"resident/{ANCHOR_MODEL}", TABLE_LOG2, "sort", "loop")


def audit_sharded() -> Optional[AnchorResult]:
    import jax

    if len(jax.devices()) < SHARDS:
        return AnchorResult(
            report=None, model_bytes=0.0, ratio=0.0, ratio_ok=True,
            skipped=f"needs {SHARDS} devices, have {len(jax.devices())} "
            "(set --xla_force_host_platform_device_count=8)",
        )
    from ..parallel.sharded import ShardedSearch

    eng = ShardedSearch(
        _model(),
        batch_size=BATCH,
        table_log2=SHARDED_TABLE_LOG2,
        append=APPEND,
    )
    return _audit(
        eng, f"sharded/{ANCHOR_MODEL}", SHARDED_TABLE_LOG2, "sort", "loop"
    )


def audit_anchors() -> dict:
    """name -> AnchorResult for every engine anchor."""
    return {
        "frontier": audit_frontier(),
        "resident": audit_resident(),
        "sharded": audit_sharded(),
    }
