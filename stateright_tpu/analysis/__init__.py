"""Project static analysis: srlint + the jaxpr auditor (ISSUE 6).

- regions.py — step-region inference (which functions run traced);
- srlint.py — the five project AST lint rules (SR001-SR005);
- auditor.py — jaxpr walker: forbidden ops + FLOP/byte/transfer totals;
- anchors.py — pinned engine anchor configs + costmodel cross-check;
- __main__.py — ``python -m stateright_tpu.analysis`` CLI.

srlint imports no jax, and neither does this package: the auditor modules
import it lazily so the lint pass (and ``--skip-audit`` CLI runs) stay
jax-free, matching the root package's host-only import discipline.
"""

from .srlint import Finding, lint_paths, lint_source  # noqa: F401
