"""State-building utility types (ref: src/util.rs, src/util/densenatmap.rs,
src/util/vector_clock.rs).

The reference needs `HashableHashSet`/`HashableHashMap` because Rust's std
collections don't implement `Hash`; in Python `frozenset` nearly suffices, but
model states also need *stable* fingerprints and dict values aren't hashable.
`HashableSet`/`HashableMap` are immutable, order-insensitive, hashable, and
stably encodable (via `__stable_encode__`, which sorts canonical per-element
encodings exactly like the reference sorts per-element hashes,
ref: src/util.rs:137-159).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

from ..core.fingerprint import stable_encode


class HashableSet:
    """Immutable unordered set usable inside model states
    (ref: src/util.rs:70-267)."""

    __slots__ = ("_items", "_canon")

    def __init__(self, items: Iterable = ()):
        canon = {}
        for item in items:
            canon[stable_encode(item)] = item
        self._canon = tuple(sorted(canon))
        self._items = tuple(canon[k] for k in self._canon)

    def add(self, item) -> "HashableSet":
        return HashableSet(self._items + (item,))

    def remove(self, item) -> "HashableSet":
        key = stable_encode(item)
        return HashableSet(
            i for i, k in zip(self._items, self._canon) if k != key
        )

    def __contains__(self, item) -> bool:
        return stable_encode(item) in self._canon

    def __iter__(self) -> Iterator:
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __eq__(self, other) -> bool:
        return isinstance(other, HashableSet) and self._canon == other._canon

    def __hash__(self) -> int:
        return hash(self._canon)

    def __stable_encode__(self):
        return ("HashableSet", self._canon)

    def __repr__(self) -> str:
        return "{" + ", ".join(repr(i) for i in self._items) + "}"


class HashableMap:
    """Immutable unordered map usable inside model states
    (ref: src/util.rs:271-463)."""

    __slots__ = ("_pairs", "_index")

    def __init__(self, pairs=()):
        if isinstance(pairs, dict):
            pairs = pairs.items()
        elif isinstance(pairs, HashableMap):
            pairs = pairs.items()
        index = {}
        for k, v in pairs:
            index[stable_encode(k)] = (k, v)
        self._index = index
        self._pairs = tuple(index[ck] for ck in sorted(index))

    def set(self, key, value) -> "HashableMap":
        return HashableMap(self._pairs + ((key, value),))

    def remove(self, key) -> "HashableMap":
        ck = stable_encode(key)
        return HashableMap(
            (k, v) for k, v in self._pairs if stable_encode(k) != ck
        )

    def get(self, key, default=None):
        entry = self._index.get(stable_encode(key))
        return default if entry is None else entry[1]

    def __getitem__(self, key):
        entry = self._index.get(stable_encode(key))
        if entry is None:
            raise KeyError(key)
        return entry[1]

    def __contains__(self, key) -> bool:
        return stable_encode(key) in self._index

    def items(self) -> Tuple[tuple, ...]:
        return self._pairs

    def keys(self):
        return tuple(k for k, _ in self._pairs)

    def values(self):
        return tuple(v for _, v in self._pairs)

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self._pairs)

    def __eq__(self, other) -> bool:
        return isinstance(other, HashableMap) and self._pairs == other._pairs

    def __hash__(self) -> int:
        return hash(tuple((stable_encode(k), stable_encode(v)) for k, v in self._pairs))

    def __stable_encode__(self):
        return ("HashableMap", self._pairs)

    def __repr__(self) -> str:
        return "{" + ", ".join(f"{k!r}: {v!r}" for k, v in self._pairs) + "}"


class DenseNatMap:
    """Immutable Vec-backed map for dense nat keys — actor `Id`s — enforcing
    contiguity (ref: src/util/densenatmap.rs:74-356)."""

    __slots__ = ("_values",)

    def __init__(self, values: Iterable = ()):
        self._values = tuple(values)

    @staticmethod
    def from_iter_keyed(pairs: Iterable[tuple]) -> "DenseNatMap":
        """Build from (key, value) pairs; keys must be exactly 0..n-1
        (panics on gaps, ref: src/util/densenatmap.rs insert)."""
        items = sorted(pairs, key=lambda kv: int(kv[0]))
        for expected, (k, _) in enumerate(items):
            if int(k) != expected:
                raise IndexError(
                    f"DenseNatMap keys must be dense: missing {expected}"
                )
        return DenseNatMap(v for _, v in items)

    def insert(self, key, value) -> "DenseNatMap":
        i = int(key)
        if i > len(self._values):
            raise IndexError(
                f"DenseNatMap insert at {i} would leave a gap "
                f"(len={len(self._values)})"
            )
        if i == len(self._values):
            return DenseNatMap(self._values + (value,))
        vals = list(self._values)
        vals[i] = value
        return DenseNatMap(vals)

    def get(self, key, default=None):
        i = int(key)
        return self._values[i] if 0 <= i < len(self._values) else default

    def __getitem__(self, key):
        return self._values[int(key)]

    def items(self):
        from ..actor import Id

        return tuple((Id(i), v) for i, v in enumerate(self._values))

    def values(self) -> tuple:
        return self._values

    def __iter__(self):
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __eq__(self, other) -> bool:
        return isinstance(other, DenseNatMap) and self._values == other._values

    def __hash__(self) -> int:
        return hash(self._values)

    def __stable_encode__(self):
        return ("DenseNatMap", self._values)

    def __repr__(self) -> str:
        return f"DenseNatMap({list(self._values)!r})"


class VectorClock:
    """Partial-order logical clock (ref: src/util/vector_clock.rs:9-275).
    Immutable; absent indices are implicitly zero."""

    __slots__ = ("_elems",)

    def __init__(self, elems: Iterable[int] = ()):
        elems = tuple(int(e) for e in elems)
        while elems and elems[-1] == 0:  # canonical: no trailing zeros
            elems = elems[:-1]
        self._elems = elems

    def get(self, index: int) -> int:
        return self._elems[index] if 0 <= index < len(self._elems) else 0

    def incremented(self, index: int) -> "VectorClock":
        n = max(len(self._elems), index + 1)
        elems = [self.get(i) for i in range(n)]
        elems[index] += 1
        return VectorClock(elems)

    def merge_max(self, other: "VectorClock") -> "VectorClock":
        n = max(len(self._elems), len(other._elems))
        return VectorClock(
            max(self.get(i), other.get(i)) for i in range(n)
        )

    def partial_cmp(self, other: "VectorClock") -> Optional[int]:
        """-1 if self < other, 0 if equal, 1 if self > other, None if
        incomparable (ref: src/util/vector_clock.rs partial_cmp)."""
        n = max(len(self._elems), len(other._elems))
        less = greater = False
        for i in range(n):
            a, b = self.get(i), other.get(i)
            if a < b:
                less = True
            elif a > b:
                greater = True
        if less and greater:
            return None
        if less:
            return -1
        if greater:
            return 1
        return 0

    def __lt__(self, other) -> bool:
        return self.partial_cmp(other) == -1

    def __le__(self, other) -> bool:
        return self.partial_cmp(other) in (-1, 0)

    def __eq__(self, other) -> bool:
        return isinstance(other, VectorClock) and self._elems == other._elems

    def __hash__(self) -> int:
        return hash(self._elems)

    def __stable_encode__(self):
        return ("VectorClock", self._elems)

    def __len__(self) -> int:
        return len(self._elems)

    def __repr__(self) -> str:
        return f"VectorClock({list(self._elems)!r})"


__all__ = ["HashableSet", "HashableMap", "DenseNatMap", "VectorClock"]
