"""Linearizability tester (ref: src/semantics/linearizability.rs).

Captures a potentially concurrent history and decides whether a total order
exists that (a) respects each thread's own order, (b) respects *real-time*
order — an operation invoked after another completed must be serialized after
it — and (c) is valid per the `SequentialSpec`.

Real-time order is tracked exactly as the reference does: upon invocation, the
tester records the index of the last completed operation of every other thread
(ref: src/semantics/linearizability.rs:7-12, 114-126); the backtracking
`serialize` rejects interleavings that would place an operation before any of
those prerequisites (ref: :193-280).

Testers are immutable: recorders return new testers, so a tester can serve as
an `ActorModel` history (auxiliary state hashed into the fingerprint).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

from . import ConsistencyTester, SequentialSpec
from .canonical import enabled as _plane_enabled


class LinearizabilityTester(ConsistencyTester):
    __slots__ = (
        "init_ref_obj",
        "history_by_thread",
        "in_flight_by_thread",
        "is_valid_history",
        "_key_cache",  # lazy identity-tuple cache (testers are immutable)
        "_hash",
        # Dedup-first verdict plane (semantics/canonical.py). None of these
        # participate in identity/encoding — they are evaluation hints:
        "_canon",  # lazy canonical form (thread-relabeled fingerprint)
        "_parent",  # the tester this one was recorded from
        "_delta",  # ("inv"|"ret", thread_id): the recording that made it
    )

    def __init__(
        self,
        init_ref_obj: SequentialSpec,
        history_by_thread: Optional[dict] = None,
        in_flight_by_thread: Optional[dict] = None,
        is_valid_history: bool = True,
    ):
        self.init_ref_obj = init_ref_obj
        # {tid: tuple of (last_completed, op, ret)}, last_completed is a tuple
        # of sorted (peer_tid, last_index) pairs.
        self.history_by_thread = history_by_thread or {}
        # {tid: (last_completed, op)}
        self.in_flight_by_thread = in_flight_by_thread or {}
        self.is_valid_history = is_valid_history

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    # -- recording (ref: src/semantics/linearizability.rs:102-157) -------------

    def on_invoke(self, thread_id, op) -> "LinearizabilityTester":
        if not self.is_valid_history or thread_id in self.in_flight_by_thread:
            # Double-invocation invalidates the history permanently.
            return self._invalidated()
        last_completed = tuple(
            sorted(
                (tid, len(hist) - 1)
                for tid, hist in self.history_by_thread.items()
                if tid != thread_id and hist
            )
        )
        in_flight = dict(self.in_flight_by_thread)
        in_flight[thread_id] = (last_completed, op)
        history = dict(self.history_by_thread)
        history.setdefault(thread_id, ())
        child = LinearizabilityTester(self.init_ref_obj, history, in_flight, True)
        # Witness-guidance hint (semantics/canonical.py): the child extends
        # this tester by one recording; the verdict plane seeds its search
        # from this tester's cached witness instead of from scratch. Only
        # stamped while the plane is live — chains are severed by plane code
        # (_seal), so a disabled plane (SR_TPU_SEMANTICS=legacy) must not
        # pin O(depth) ancestry per live tester.
        if _plane_enabled():
            child._parent = self
            child._delta = ("inv", thread_id)
        return child

    def on_return(self, thread_id, ret) -> "LinearizabilityTester":
        if not self.is_valid_history or thread_id not in self.in_flight_by_thread:
            return self._invalidated()
        in_flight = dict(self.in_flight_by_thread)
        last_completed, op = in_flight.pop(thread_id)
        history = dict(self.history_by_thread)
        history[thread_id] = history.get(thread_id, ()) + ((last_completed, op, ret),)
        child = LinearizabilityTester(self.init_ref_obj, history, in_flight, True)
        if _plane_enabled():
            child._parent = self
            child._delta = ("ret", thread_id)
        return child

    def _invalidated(self) -> "LinearizabilityTester":
        return LinearizabilityTester(
            self.init_ref_obj,
            self.history_by_thread,
            self.in_flight_by_thread,
            False,
        )

    def is_consistent(self) -> bool:
        """The dedup-first verdict path (semantics/canonical.py): canonical
        fingerprint cache -> witness-guided incremental serialization ->
        full search, boolean-identical to `serialized_history() is not
        None` but ~one search per equivalence class per process instead of
        one per distinct history. Properties should call THIS."""
        from .canonical import verdict

        return verdict(self)

    # -- serialization search (ref: src/semantics/linearizability.rs:175-280) --

    def serialized_history(self) -> Optional[list]:
        """A valid total order of (op, ret) pairs, or None. In-flight ops may
        appear (they might have taken effect) or not (they might not have).
        Exact legacy search order — pinned witness lists never change; the
        canonical plane only short-circuits the verdict-equivalent negative
        (a cached False IS None)."""
        if not self.is_valid_history:
            return None
        from .canonical import probe_cached_negative

        if probe_cached_negative(self):
            return None
        cached = _serialized_cached(self)
        return None if cached is None else list(cached)

    def _serialized_uncached(self) -> Optional[list]:
        from ._native_bridge import NOT_SUPPORTED, native_serialized_history

        native = native_serialized_history(
            self.init_ref_obj,
            self.history_by_thread,
            self.in_flight_by_thread,
            linearizable=True,
        )
        if native is not NOT_SUPPORTED:
            return native
        remaining = {
            tid: tuple(enumerate(hist))
            for tid, hist in self.history_by_thread.items()
        }
        return _serialize([], self.init_ref_obj, remaining, self.in_flight_by_thread)

    # -- identity (the tester lives inside checker states) ---------------------

    def _key(self):
        # Testers are immutable (every recording op returns a new tester),
        # so the identity tuple is built once and cached — `_key` dominates
        # host hashing costs otherwise (exact-closure profile, round 4).
        k = getattr(self, "_key_cache", None)
        if k is None:
            k = self._key_cache = (
                self.init_ref_obj,
                frozenset(self.history_by_thread.items()),
                frozenset(self.in_flight_by_thread.items()),
                self.is_valid_history,
            )
        return k

    def __stable_encode__(self):
        return (
            type(self).__name__,
            self.init_ref_obj,
            self.history_by_thread,
            self.in_flight_by_thread,
            self.is_valid_history,
        )

    def __eq__(self, other) -> bool:
        return isinstance(other, type(self)) and self._key() == other._key()

    def __hash__(self) -> int:
        h = getattr(self, "_hash", None)
        if h is None:
            h = self._hash = hash(self._key())
        return h

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r}, valid={self.is_valid_history})"
        )


@lru_cache(maxsize=1 << 15)
def _serialized_cached(tester: "LinearizabilityTester"):
    """Equal testers recur across many checker states (the history is only one
    component of the state), so the search result is memoized on the immutable
    tester (SURVEY.md §7: "cache verdicts by history-fingerprint")."""
    result = tester._serialized_uncached()
    if result is None:
        # Feed the canonical plane the refutation for free: a negative is a
        # class-wide fact `serialized_history` can short-circuit on later
        # (positives are not recorded here — the legacy list is
        # label-specific and a positive cannot skip the legacy search, so
        # canonicalizing every positive would be pure overhead).
        from .canonical import note_verdict

        note_verdict(tester, False)
        return None
    return tuple(result)


def verdict_cache_stats() -> dict:
    """The verdict planes' counters (ROADMAP item 5): the legacy
    per-identity lru memo plus the dedup-first canonical plane
    (semantics/canonical.py: class collapse, witness guidance, batch
    evaluation, corpus preloads). Exported through the obs REGISTRY
    ("semantics" source) and pinned by tests/test_semantics.py."""
    from . import sequential_consistency as _sc
    from .canonical import CACHE

    info = _serialized_cached.cache_info()
    sc_info = _sc._serialized_cached.cache_info()
    out = {
        "verdict_cache_hits": info.hits + sc_info.hits,
        "verdict_cache_misses": info.misses + sc_info.misses,
        "verdict_cache_entries": info.currsize + sc_info.currsize,
    }
    out.update(CACHE.stats())
    return out


# Module-level registration: the cache is process-global (the lru_cache
# above), so its counters register once at import — `/metrics` on any
# Explorer/service server then reports cache effectiveness live.
from ..obs import REGISTRY  # noqa: E402  (after the cache it exports)

REGISTRY.register("semantics", verdict_cache_stats)


def _violates_real_time(last_completed, remaining) -> bool:
    """An op cannot serialize before its prerequisites: every peer op up to the
    recorded index must already be consumed (ref: linearizability.rs:221-233)."""
    for peer_id, min_peer_time in last_completed:
        ops = remaining.get(peer_id)
        if ops:
            next_peer_time = ops[0][0]
            if next_peer_time <= min_peer_time:
                return True
    return False


def _serialize(valid_history, ref_obj, remaining, in_flight) -> Optional[list]:
    if all(not h for h in remaining.values()):
        # In-flight ops need not take effect (ref: linearizability.rs:203-208).
        return valid_history

    for thread_id in remaining:
        history = remaining[thread_id]
        if not history:
            # Case 1: only a possibly-in-flight op remains for this thread.
            if thread_id not in in_flight:
                continue
            last_completed, op = in_flight[thread_id]
            if _violates_real_time(last_completed, remaining):
                continue
            ret, next_obj = ref_obj.invoke(op)
            next_in_flight = {t: v for t, v in in_flight.items() if t != thread_id}
            result = _serialize(
                valid_history + [(op, ret)], next_obj, remaining, next_in_flight
            )
            if result is not None:
                return result
        else:
            # Case 2: consume the thread's next completed op.
            (_idx, (last_completed, op, ret)) = history[0]
            next_remaining = dict(remaining)
            next_remaining[thread_id] = history[1:]
            if _violates_real_time(last_completed, next_remaining):
                continue
            next_obj = ref_obj.is_valid_step(op, ret)
            if next_obj is None:
                continue
            result = _serialize(
                valid_history + [(op, ret)], next_obj, next_remaining, in_flight
            )
            if result is not None:
                return result
    return None
