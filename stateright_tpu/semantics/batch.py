"""Batched parallel verdict plane (dedup-first semantics, ROADMAP item 5).

At every checker chunk boundary the host engines hand this module the
post-dedup batch's consistency testers in one call. The plane:

1. canonicalizes each tester and COLLAPSES the batch to unique equivalence
   classes (`canonical_collapsed` counts the savings),
2. resolves classes cheaply in deterministic order — cache probe, then
   witness guidance off parents (shorter histories are evaluated first, so a
   child's parent is usually already resolved a few iterations earlier),
3. runs the full canonical search only for the surviving classes —
   concurrently through a thread pool when the native serializer is
   available (the ctypes call releases the GIL), serially in the same
   deterministic order otherwise. Verdicts are order-independent pure
   functions of the canonical class, so pool scheduling cannot change any
   result: serial and parallel runs are bit-identical by construction.

The packed (canonical fingerprint, verdict bit) table round-trips through
the warm-start corpus (store/corpus.py): `export_verdicts` rides in every
published entry, `preload_verdicts` seeds the cache at admission — verdict
bits are content-addressed by canonical class, so a table computed by any
job is valid for every other.
"""

from __future__ import annotations

import time
from typing import Iterable, Optional

from . import ConsistencyTester
from .canonical import (
    CACHE,
    enabled,
    probe_verdict,
    search_steps,
    try_canonical_form,
)

#: Default thread-pool width for the native-backed parallel phase. The pool
#: only materializes when there are >= 2 unresolved classes and the native
#: serializer loaded; pure-Python fallbacks run serially (the GIL would
#: serialize them anyway).
PARALLEL_WORKERS = 4

#: Below this many unresolved classes the pool's spawn overhead exceeds the
#: search time saved.
_PARALLEL_MIN = 2


def _native_available() -> bool:
    from . import _native_bridge

    return _native_bridge._load() is not None


def evaluate_batch(
    testers: Iterable, parallel: Optional[bool] = None
) -> list:
    """Verdicts (booleans) for `testers`, positionally. The workhorse of the
    chunk-boundary prefetch: one call per post-dedup batch instead of one
    cache probe (and too often one search) per state mid-loop."""
    testers = list(testers)
    out = [False] * len(testers)
    if not testers:
        return out
    if not enabled():
        for i, t in enumerate(testers):
            out[i] = t.serialized_history() is not None
        return out

    t0 = time.perf_counter()
    # 1a. Identity pre-dedup: equal testers recur across many states of a
    # batch, and tester hash/eq are memoized — collapse those FIRST so
    # canonicalization runs once per distinct history, not once per state.
    ident: dict = {}  # distinct tester -> [output indices]
    for i, t in enumerate(testers):
        if not isinstance(t, ConsistencyTester):
            raise TypeError(f"not a ConsistencyTester: {t!r}")
        if not t.is_valid_history:
            continue  # verdict False, no class needed
        ident.setdefault(t, []).append(i)

    # 1b. Canonicalize + collapse identities to equivalence classes
    # (thread-relabeled histories). Testers whose history cannot
    # canonicalize (exotic user specs) take the legacy memo path.
    by_fp: dict = {}
    slots: dict = {}  # fp -> [output indices]
    n_canon = 0  # identities that actually canonicalized (collapse basis)
    for t, idxs in ident.items():
        form = try_canonical_form(t)
        if form is None:
            v = t.serialized_history() is not None
            for i in idxs:
                out[i] = v
            continue
        n_canon += 1
        if form.fp not in by_fp:
            by_fp[form.fp] = t
        slots.setdefault(form.fp, []).extend(idxs)
    CACHE._count("canonical_collapsed", n_canon - len(by_fp))

    # 2. Deterministic cheap pass, shallowest recordings first: cache probes
    # + witness guidance off classes already resolved (possibly by an
    # earlier batch or a corpus preload). The key is the RECORDING rank, not
    # op count — an `on_return` child has the same op count as its parent
    # (in-flight became completed), but rank is strictly +1 per recording,
    # so a parent class always orders before its children.
    order = sorted(
        by_fp, key=lambda fp: (try_canonical_form(by_fp[fp]).rank, fp)
    )
    verdicts: dict = {}
    pending: list = []
    for fp in order:
        got = probe_verdict(by_fp[fp])
        if got is not None:
            verdicts[fp] = got
        else:
            pending.append(fp)

    # 3. Split the survivors: a class whose PARENT class is also unresolved
    # in this batch chains — its search can be witness-guided once the
    # parent lands, so those resolve serially parent-first. Everything else
    # is an independent root: full search now, concurrently through the
    # native serializer when available (the ctypes call releases the GIL).
    if pending:
        pending_set = set(pending)

        def parent_class(t):
            p = getattr(t, "_parent", None)
            if p is None or not p.is_valid_history:
                return None
            pf = try_canonical_form(p)
            return None if pf is None else pf.fp

        chained = [
            fp for fp in pending
            if parent_class(by_fp[fp]) in pending_set
        ]
        chained_set = set(chained)
        roots = [fp for fp in pending if fp not in chained_set]

        use_pool = (
            (parallel if parallel is not None else len(roots) >= _PARALLEL_MIN)
            and len(roots) >= _PARALLEL_MIN
            and _native_available()
        )

        def run(fp):
            steps = search_steps(try_canonical_form(by_fp[fp]))
            return fp, steps

        if use_pool:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(PARALLEL_WORKERS, len(roots)),
                thread_name_prefix="semantics-verdict",
            ) as pool:
                results = list(pool.map(run, roots))
            CACHE._count("batch_parallel_evals", len(roots))
        else:
            results = [run(fp) for fp in roots]
        from .canonical import _seal

        for fp, steps in results:
            CACHE._count("canonical_misses")
            CACHE._count("full_searches")
            CACHE.put(fp, steps is not None, steps)
            _seal(by_fp[fp])
            verdicts[fp] = steps is not None

        # Chained classes, parent-first (the sort above put every parent
        # before its children — one recording adds exactly one rank).
        for fp in chained:
            got = probe_verdict(by_fp[fp])
            if got is None:
                CACHE._count("canonical_misses")
                steps = search_steps(try_canonical_form(by_fp[fp]))
                CACHE._count("full_searches")
                CACHE.put(fp, steps is not None, steps)
                _seal(by_fp[fp])
                got = steps is not None
            verdicts[fp] = got

    # 4. Scatter back to states.
    for fp, idxs in slots.items():
        v = verdicts[fp]
        for i in idxs:
            out[i] = v
    dt_ms = (time.perf_counter() - t0) * 1000.0
    with CACHE._lock:
        CACHE.counters["batch_evals"] += 1
        CACHE.counters["batch_states"] += len(testers)
        CACHE.counters["batch_eval_ms_total"] += dt_ms
        CACHE.counters["batch_eval_ms_last"] = dt_ms
    return out


def prefetch_verdicts(testers: Iterable) -> int:
    """Warm the canonical cache for a batch (checker chunk boundaries,
    lowering history closures). Returns the number of testers considered.
    Never raises — the plane is an optimization, property evaluation still
    decides on its own."""
    batch = [
        t for t in testers
        if isinstance(t, ConsistencyTester) and t.is_valid_history
    ]
    if len(batch) < 2 or not enabled():
        return 0
    evaluate_batch(batch)
    return len(batch)


def collect_history_testers(model, cap: int):
    """A register-model anchor's post-dedup batch: unique states' history
    testers, enumerated depth-first (deep states carry the long, contended
    histories where backtracking blows up). Returns (testers, unique_count).
    Shared by bench.py's BENCH_SEMANTICS worker and
    scripts/semantics_smoke.py so the A/B and the smoke measure the same
    batch shape."""
    from ..core.fingerprint import fingerprint

    seen, testers, stack = set(), [], []
    for s in model.init_states():
        seen.add(fingerprint(s))
        stack.append(s)
        testers.append(s.history)
    while stack and len(testers) < cap:
        s = stack.pop()
        actions: list = []
        model.actions(s, actions)
        for a in actions:
            ns = model.next_state(s, a)
            if ns is None:
                continue
            fp = fingerprint(ns)
            if fp in seen:
                continue
            seen.add(fp)
            stack.append(ns)
            testers.append(ns.history)
    return testers, len(seen)


# -- corpus round-trip ---------------------------------------------------------


def export_verdicts():
    """(uint64 fingerprints, uint8 verdict bits) — the packed table the
    corpus publishes with every entry (store/corpus.py)."""
    return CACHE.export()


def preload_verdicts(fps, verdicts) -> int:
    """Seed the cache from a corpus table; returns NEW entries inserted."""
    import numpy as np

    fps = np.asarray(fps, dtype=np.uint64)
    verdicts = np.asarray(verdicts, dtype=np.uint8)
    if fps.size == 0 or fps.shape != verdicts.shape:
        return 0
    return CACHE.preload(fps, verdicts)
