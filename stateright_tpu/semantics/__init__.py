"""Consistency semantics: reference objects and concurrent-history testers
(ref: src/semantics.rs).

`SequentialSpec` defines correctness via a reference implementation ("this
system should behave like a register/stack"). A `ConsistencyTester` records a
potentially concurrent history of per-thread invocations/returns and decides
whether it can be serialized under a consistency model — linearizability
(real-time order respected) or sequential consistency (per-thread order only).

Unlike the reference's mutate-in-place specs, specs and testers here are
IMMUTABLE: `invoke` returns `(ret, new_spec)` and tester recorders return new
testers, so they can live inside checker states directly (the tester IS the
`ActorModel` history type, hashed into the state fingerprint — see
stateright_tpu.actor.register for the wiring, and SURVEY.md §2.5 for the
integration pattern).
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Tuple


class SequentialSpec:
    """A sequential reference object (ref: src/semantics.rs:73-98)."""

    def invoke(self, op) -> Tuple[Any, "SequentialSpec"]:
        """Apply `op`; return (ret, next_spec)."""
        raise NotImplementedError

    def is_valid_step(self, op, ret) -> Optional["SequentialSpec"]:
        """If invoking `op` can return `ret`, the next spec state; else None."""
        actual_ret, next_spec = self.invoke(op)
        return next_spec if actual_ret == ret else None

    def is_valid_history(self, pairs: Iterable[tuple]) -> bool:
        spec: Optional[SequentialSpec] = self
        for op, ret in pairs:
            spec = spec.is_valid_step(op, ret)
            if spec is None:
                return False
        return True


class ConsistencyTester:
    """Records per-thread operation histories
    (ref: src/semantics/consistency_tester.rs:15-43).

    Recorders return a NEW tester; an invalid recording (double in-flight op,
    return without invocation) yields a tester whose histories can never
    serialize."""

    def on_invoke(self, thread_id, op) -> "ConsistencyTester":
        raise NotImplementedError

    def on_return(self, thread_id, ret) -> "ConsistencyTester":
        raise NotImplementedError

    def on_invret(self, thread_id, op, ret) -> "ConsistencyTester":
        return self.on_invoke(thread_id, op).on_return(thread_id, ret)

    def is_consistent(self) -> bool:
        raise NotImplementedError


from .register import (  # noqa: E402
    Register,
    WORegister,
    Write,
    Read,
    WriteOk,
    WriteFail,
    ReadOk,
)
from .vec import VecSpec, Push, Pop, Len, PushOk, PopOk, LenOk  # noqa: E402
from .linearizability import LinearizabilityTester  # noqa: E402
from .sequential_consistency import SequentialConsistencyTester  # noqa: E402


def clear_serialization_caches() -> None:
    """Drop the memoized serialization verdicts (they pin tester histories in
    memory for the process lifetime otherwise). Call between unrelated long
    checker runs if memory matters. Clears BOTH planes: the per-identity
    lru memos and the canonical verdict cache (witnesses included)."""
    from . import canonical, linearizability, sequential_consistency

    linearizability._serialized_cached.cache_clear()
    sequential_consistency._serialized_cached.cache_clear()
    canonical.CACHE.clear()


#: `maintain_caches` trims the canonical plane back under this fraction of
#: its bound and clears a legacy lru memo that crossed the same bar. The
#: legacy memos pin FULL histories (tester objects are the keys), so a
#: long-lived service replica serving thousands of register jobs would
#: otherwise grow until the lru maxsize (2^15 testers) of RETAINED history
#: tuples per memo.
MAINTAIN_MAX_ENTRIES = 1 << 14


def maintain_caches(max_entries: int = MAINTAIN_MAX_ENTRIES) -> dict:
    """Bound the verdict caches for long-lived services: called by the check
    service at every job finalize (service/scheduler.py). The canonical
    cache LRU-trims (cheap, keeps the hot classes); an oversized legacy lru
    memo is cleared outright (functools.lru_cache cannot partially shrink).
    Returns {trimmed, legacy_cleared} and counts both through the
    "semantics" REGISTRY source."""
    from . import canonical, linearizability, sequential_consistency

    trimmed = 0
    if len(canonical.CACHE) > max_entries:
        trimmed = canonical.CACHE.trim(max_entries)
    legacy_cleared = 0
    for mod in (linearizability, sequential_consistency):
        if mod._serialized_cached.cache_info().currsize > max_entries:
            mod._serialized_cached.cache_clear()
            legacy_cleared += 1
    if legacy_cleared:
        canonical.CACHE._count("legacy_clears", legacy_cleared)
    return {"trimmed": trimmed, "legacy_cleared": legacy_cleared}


__all__ = [
    "clear_serialization_caches",
    "maintain_caches",
    "SequentialSpec",
    "ConsistencyTester",
    "Register",
    "WORegister",
    "Write",
    "Read",
    "WriteOk",
    "WriteFail",
    "ReadOk",
    "VecSpec",
    "Push",
    "Pop",
    "Len",
    "PushOk",
    "PopOk",
    "LenOk",
    "LinearizabilityTester",
    "SequentialConsistencyTester",
]
