"""Consistency semantics: reference objects and concurrent-history testers
(ref: src/semantics.rs).

`SequentialSpec` defines correctness via a reference implementation ("this
system should behave like a register/stack"). A `ConsistencyTester` records a
potentially concurrent history of per-thread invocations/returns and decides
whether it can be serialized under a consistency model — linearizability
(real-time order respected) or sequential consistency (per-thread order only).

Unlike the reference's mutate-in-place specs, specs and testers here are
IMMUTABLE: `invoke` returns `(ret, new_spec)` and tester recorders return new
testers, so they can live inside checker states directly (the tester IS the
`ActorModel` history type, hashed into the state fingerprint — see
stateright_tpu.actor.register for the wiring, and SURVEY.md §2.5 for the
integration pattern).
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Tuple


class SequentialSpec:
    """A sequential reference object (ref: src/semantics.rs:73-98)."""

    def invoke(self, op) -> Tuple[Any, "SequentialSpec"]:
        """Apply `op`; return (ret, next_spec)."""
        raise NotImplementedError

    def is_valid_step(self, op, ret) -> Optional["SequentialSpec"]:
        """If invoking `op` can return `ret`, the next spec state; else None."""
        actual_ret, next_spec = self.invoke(op)
        return next_spec if actual_ret == ret else None

    def is_valid_history(self, pairs: Iterable[tuple]) -> bool:
        spec: Optional[SequentialSpec] = self
        for op, ret in pairs:
            spec = spec.is_valid_step(op, ret)
            if spec is None:
                return False
        return True


class ConsistencyTester:
    """Records per-thread operation histories
    (ref: src/semantics/consistency_tester.rs:15-43).

    Recorders return a NEW tester; an invalid recording (double in-flight op,
    return without invocation) yields a tester whose histories can never
    serialize."""

    def on_invoke(self, thread_id, op) -> "ConsistencyTester":
        raise NotImplementedError

    def on_return(self, thread_id, ret) -> "ConsistencyTester":
        raise NotImplementedError

    def on_invret(self, thread_id, op, ret) -> "ConsistencyTester":
        return self.on_invoke(thread_id, op).on_return(thread_id, ret)

    def is_consistent(self) -> bool:
        raise NotImplementedError


from .register import (  # noqa: E402
    Register,
    WORegister,
    Write,
    Read,
    WriteOk,
    WriteFail,
    ReadOk,
)
from .vec import VecSpec, Push, Pop, Len, PushOk, PopOk, LenOk  # noqa: E402
from .linearizability import LinearizabilityTester  # noqa: E402
from .sequential_consistency import SequentialConsistencyTester  # noqa: E402


def clear_serialization_caches() -> None:
    """Drop the memoized serialization verdicts (they pin tester histories in
    memory for the process lifetime otherwise). Call between unrelated long
    checker runs if memory matters."""
    from . import linearizability, sequential_consistency

    linearizability._serialized_cached.cache_clear()
    sequential_consistency._serialized_cached.cache_clear()


__all__ = [
    "clear_serialization_caches",
    "SequentialSpec",
    "ConsistencyTester",
    "Register",
    "WORegister",
    "Write",
    "Read",
    "WriteOk",
    "WriteFail",
    "ReadOk",
    "VecSpec",
    "Push",
    "Pop",
    "Len",
    "PushOk",
    "PopOk",
    "LenOk",
    "LinearizabilityTester",
    "SequentialConsistencyTester",
]
