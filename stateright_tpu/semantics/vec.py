"""Stack (Vec) reference object (ref: src/semantics/vec.rs)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from . import SequentialSpec


@dataclass(frozen=True)
class Push:
    value: Any


@dataclass(frozen=True)
class Pop:
    pass


@dataclass(frozen=True)
class Len:
    pass


@dataclass(frozen=True)
class PushOk:
    pass


@dataclass(frozen=True)
class PopOk:
    value: Any  # None when empty


@dataclass(frozen=True)
class LenOk:
    length: int


@dataclass(frozen=True)
class VecSpec(SequentialSpec):
    """Stack semantics: Push/Pop/Len (ref: src/semantics/vec.rs:22-50)."""

    items: tuple = ()

    def invoke(self, op) -> Tuple[Any, "VecSpec"]:
        if isinstance(op, Push):
            return PushOk(), VecSpec(self.items + (op.value,))
        if isinstance(op, Pop):
            if self.items:
                return PopOk(self.items[-1]), VecSpec(self.items[:-1])
            return PopOk(None), self
        if isinstance(op, Len):
            return LenOk(len(self.items)), self
        raise TypeError(f"not a vec op: {op!r}")
