"""Bridge between the Python consistency testers and the native serializer.

Encodes a tester's history into flat int64 arrays, calls the C++ backtracking
search (stateright_tpu/_native/serialize.cpp), and decodes the returned
interleaving back into (op, ret) pairs by replaying it through the Python
spec. Only the built-in reference objects (Register, WORegister, VecSpec) with
hashable payloads take this path; anything else returns NOT_SUPPORTED and the
caller runs the Python search. The native search visits interleavings in the
same order as the Python one, so results are identical, not merely equivalent.
"""

from __future__ import annotations

import ctypes
from typing import Optional

from .register import Read, Register, WORegister, Write, WriteFail, WriteOk
from .vec import Len, Pop, Push, VecSpec

NOT_SUPPORTED = object()  # sentinel: caller must use the Python search

# Below this many ops (completed + in flight) the Python search finishes in
# ~10us and the ctypes marshalling (~40-100us) would be a net loss; the native
# search exists for the larger histories where backtracking grows
# exponentially. Measured crossover on Register histories: python stays
# 12-17us through 12 easy ops but blows up on contended ones.
NATIVE_MIN_OPS = 12

_SPEC_REGISTER, _SPEC_WO_REGISTER, _SPEC_VEC = 0, 1, 2
_OP_WRITE, _OP_READ = 0, 1
_OP_PUSH, _OP_POP, _OP_LEN = 0, 1, 2

_i64 = ctypes.c_int64
_i32 = ctypes.c_int32
_u8 = ctypes.c_uint8

_lib = None
_lib_loaded = False


def _load():
    global _lib, _lib_loaded
    if not _lib_loaded:
        from .. import _native

        _lib = _native.load("serialize")
        if _lib is not None:
            _lib.srt_serialize.restype = ctypes.c_int32
        _lib_loaded = True
    return _lib


class _Interner:
    """Dense int64 ids for op/ret payloads, in first-seen order."""

    def __init__(self):
        self.ids: dict = {}

    def __call__(self, value) -> Optional[int]:
        try:
            got = self.ids.get(value)
        except TypeError:  # unhashable payload
            return None
        if got is None:
            got = len(self.ids)
            self.ids[value] = got
        return got


def _encode_op(op, intern, is_vec: bool):
    """(kind, val) or None when the op isn't one this spec understands."""
    if is_vec:
        if isinstance(op, Push):
            v = intern(op.value)
            return None if v is None else (_OP_PUSH, v)
        if isinstance(op, Pop):
            return (_OP_POP, 0)
        if isinstance(op, Len):
            return (_OP_LEN, 0)
        return None
    if isinstance(op, Write):
        v = intern(op.value)
        return None if v is None else (_OP_WRITE, v)
    if isinstance(op, Read):
        return (_OP_READ, 0)
    return None


def _encode_ret(ret, intern, is_vec: bool):
    from .register import ReadOk
    from .vec import LenOk, PopOk, PushOk

    if is_vec:
        if isinstance(ret, PushOk):
            return (0, 0)
        if isinstance(ret, PopOk):
            v = intern(ret.value)
            return None if v is None else (1, v)
        if isinstance(ret, LenOk):
            return (2, int(ret.length))
        return None
    if isinstance(ret, WriteOk):
        return (0, 0)
    if isinstance(ret, WriteFail):
        return (1, 0)
    if isinstance(ret, ReadOk):
        v = intern(ret.value)
        return None if v is None else (2, v)
    return None


def native_serialize_steps(
    init_ref_obj,
    history_by_thread: dict,
    in_flight_by_thread: dict,
    linearizable: bool,
    min_ops: int = NATIVE_MIN_OPS,
):
    """The raw witness as (thread_id, from_in_flight) steps, None (not
    serializable), or NOT_SUPPORTED. Thread ids are the caller's own dict
    keys — the canonical verdict plane (semantics/canonical.py) passes
    canonically-relabeled dicts and gets canonical steps back, skipping
    the (op, ret) decode replay entirely. `min_ops` gates the marshalling
    overhead: the default protects repeated per-call sites, while the
    canonical plane lowers it (each of its searches runs once per
    equivalence class, so the ~100us ctypes cost always amortizes)."""
    n_ops = len(in_flight_by_thread) + sum(
        len(h) for h in history_by_thread.values()
    )
    if n_ops < min_ops:
        return NOT_SUPPORTED
    lib = _load()
    if lib is None:
        return NOT_SUPPORTED

    # Exact types only: a user subclass may override invoke/is_valid_step, so
    # it must take the Python path like any other custom spec.
    spec_type = type(init_ref_obj)
    if spec_type is WORegister:
        spec_kind, is_vec = _SPEC_WO_REGISTER, False
    elif spec_type is Register:
        spec_kind, is_vec = _SPEC_REGISTER, False
    elif spec_type is VecSpec:
        spec_kind, is_vec = _SPEC_VEC, True
    else:
        return NOT_SUPPORTED

    intern = _Interner()
    none_id = intern(None)

    if spec_kind == _SPEC_REGISTER:
        v = intern(init_ref_obj.value)
        spec_state = [v]
    elif spec_kind == _SPEC_WO_REGISTER:
        v = intern(init_ref_obj.value)
        spec_state = [v, 1 if init_ref_obj.written else 0]
    else:
        vals = [intern(x) for x in init_ref_obj.items]
        if any(x is None for x in vals):
            return NOT_SUPPORTED
        spec_state = vals
        v = 0
    if v is None:
        return NOT_SUPPORTED

    # Dense thread ids in the Python dict's iteration order (the search order).
    tids = list(history_by_thread)
    tix = {tid: i for i, tid in enumerate(tids)}
    T = len(tids)
    if any(tid not in tix for tid in in_flight_by_thread):
        return NOT_SUPPORTED  # never happens via the recorders

    hist_offset = [0]
    op_kind, op_val, ret_kind, ret_val = [], [], [], []
    prereq_offset = [0]
    prereq_peer, prereq_time = [], []
    for tid in tids:
        for entry in history_by_thread[tid]:
            if linearizable:
                last_completed, op, ret = entry
            else:
                op, ret = entry
                last_completed = ()
            eo = _encode_op(op, intern, is_vec)
            er = _encode_ret(ret, intern, is_vec)
            if eo is None or er is None:
                return NOT_SUPPORTED
            op_kind.append(eo[0])
            op_val.append(eo[1])
            ret_kind.append(er[0])
            ret_val.append(er[1])
            for peer, min_time in last_completed:
                prereq_peer.append(tix[peer])
                prereq_time.append(min_time)
            prereq_offset.append(len(prereq_peer))
        hist_offset.append(len(op_kind))
    N = len(op_kind)

    ifl_present = [0] * T
    ifl_op_kind = [0] * T
    ifl_op_val = [0] * T
    ifl_prereq_offset = [0] * (T + 1)
    ifl_prereq_peer, ifl_prereq_time = [], []
    for t, tid in enumerate(tids):
        if tid in in_flight_by_thread:
            entry = in_flight_by_thread[tid]
            if linearizable:
                last_completed, op = entry
            else:
                op, last_completed = entry, ()
            eo = _encode_op(op, intern, is_vec)
            if eo is None:
                return NOT_SUPPORTED
            ifl_present[t] = 1
            ifl_op_kind[t], ifl_op_val[t] = eo
            for peer, min_time in last_completed:
                ifl_prereq_peer.append(tix[peer])
                ifl_prereq_time.append(min_time)
        ifl_prereq_offset[t + 1] = len(ifl_prereq_peer)

    def arr(ctype, values):
        return (ctype * max(len(values), 1))(*values)

    out_thread = (_i32 * (N + T))()
    out_ifl = (_u8 * (N + T))()
    out_len = _i64(0)
    rc = lib.srt_serialize(
        _i32(spec_kind),
        _i32(1 if linearizable else 0),
        arr(_i64, spec_state),
        _i64(len(spec_state)),
        _i64(none_id),
        _i32(T),
        arr(_i64, hist_offset),
        arr(_i32, op_kind),
        arr(_i64, op_val),
        arr(_i32, ret_kind),
        arr(_i64, ret_val),
        arr(_i64, prereq_offset),
        arr(_i64, prereq_peer),
        arr(_i64, prereq_time),
        arr(_u8, ifl_present),
        arr(_i32, ifl_op_kind),
        arr(_i64, ifl_op_val),
        arr(_i64, ifl_prereq_offset),
        arr(_i64, ifl_prereq_peer),
        arr(_i64, ifl_prereq_time),
        out_thread,
        out_ifl,
        ctypes.byref(out_len),
    )
    if rc == 0:
        return None
    if rc != 1:
        return NOT_SUPPORTED
    return [
        (tids[out_thread[i]], bool(out_ifl[i]))
        for i in range(out_len.value)
    ]


def native_serialized_history(
    init_ref_obj,
    history_by_thread: dict,
    in_flight_by_thread: dict,
    linearizable: bool,
):
    """A serialized history list, None (not serializable), or NOT_SUPPORTED."""
    steps = native_serialize_steps(
        init_ref_obj, history_by_thread, in_flight_by_thread, linearizable
    )
    if steps is None or steps is NOT_SUPPORTED:
        return steps

    # Decode: replay the chosen interleaving through the Python spec so the
    # returned (op, ret) pairs are the exact Python objects.
    pos = {tid: 0 for tid in history_by_thread}
    spec = init_ref_obj
    out = []
    for tid, from_ifl in steps:
        if from_ifl:
            entry = in_flight_by_thread[tid]
            op = entry[1] if linearizable else entry
            ret, spec = spec.invoke(op)
        else:
            entry = history_by_thread[tid][pos[tid]]
            pos[tid] += 1
            if linearizable:
                _, op, ret = entry
            else:
                op, ret = entry
            spec = spec.is_valid_step(op, ret)
            if spec is None:
                # Native/Python semantics drift — never silently trust the
                # native result; let the Python search decide.
                return NOT_SUPPORTED
        out.append((op, ret))
    return out
