"""Dedup-first verdict plane: canonical history fingerprints + witness-guided
incremental serialization (ROADMAP item 5, SURVEY §7 "cache verdicts by
history-fingerprint" promoted to a dedup-first design).

The serialization verdict of a concurrent history — "does a valid total order
exist?" — is invariant under THREAD RELABELING: the backtracking search uses
thread identity only to group per-thread sequences and resolve real-time
prerequisite references, both of which relabel covariantly. This module
exploits that three ways:

1. **Canonical fingerprints.** A tester is canonicalized by reordering its
   threads deterministically by label-free content signatures (a one-round
   Weisfeiler-Lehman refinement: per-thread op/ret sequences first, then
   prerequisite references expressed through peers' round-0 signatures).
   The canonical encoding — relabeled histories, remapped prerequisite sets,
   the reference spec — hashes to a 64-bit fingerprint; thread-relabeled
   histories that would each miss the per-identity lru memo collapse to ONE
   cache entry per equivalence class. This composes with tensor/symmetry.py's
   reduction argument: the representative's verdict IS every class member's.

2. **Witness-guided incremental serialization.** Verdicts are cached with a
   *witness* — the serialization as (canonical thread, from-in-flight) steps,
   reconstructible for any class member. Recorders stamp each new tester with
   a reference to its parent plus the recording delta, so when a tester
   extends an already-verified parent (the common case: every `on_return`
   during checker expansion extends a verified history by one op) the search
   is seeded from the parent's witness instead of from scratch:

   - `on_invoke` child, parent serializable: the parent's witness is a valid
     serialization of the child verbatim (in-flight ops need not take
     effect) — verdict True in O(n) validation.
   - `on_return` child, parent NOT serializable: any serialization of the
     child is one of the parent (the completed op re-read as the in-flight
     op having taken effect — `invoke` is deterministic, so the recorded
     return is exactly what inclusion would have produced), so the child is
     not serializable either — verdict False with NO search. This kills the
     expensive exhaustive-refutation searches along invalid-history chains.
     The proof needs `is_valid_step` to accept exactly what `invoke`
     produces, so the rule is gated on `_deterministic_invoke` (base-class
     `is_valid_step` or an explicit `invoke_deterministic = True`); specs
     with a more permissive override skip it and keep the full search.
   - `on_return` child, parent serializable: flip the parent witness's
     in-flight step for that thread to a completed step, or insert the new
     completed step at each position from the tail; every candidate is
     O(n)-validated (never trusted), falling back to the full search only
     when all candidates fail.

   Candidate validation is sound by construction (a validated witness IS a
   serialization), so guidance can only ever skip work, never change a
   verdict.

3. **A process-global bounded verdict cache** keyed by canonical fingerprint,
   shared by both tester kinds (the kind is folded into the fingerprint),
   batch-populated by `semantics.batch`, warm-started across jobs through the
   corpus (store/corpus.py packs the (fingerprint, verdict-bit) table into
   every published entry), and trimmed at service job finalize so a fleet
   replica serving thousands of register jobs stops growing without bound.

`serialized_history()` keeps its EXACT legacy behavior (same witness lists,
same search order) — the canonical plane short-circuits only the
verdict-equivalent cases (a cached False is returned as None directly; a
cached True still runs the legacy search for the legacy witness), so all
pinned witness assertions and goldens stay bit-identical.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

from ..core.fingerprint import fingerprint_bytes, stable_encode

#: Upper bound on resident verdict-cache entries; `trim()` (called at service
#: job finalize) shrinks back under it. Generous for single checks, bounded
#: for long-lived services.
CACHE_MAX_ENTRIES = 1 << 16

#: Per-corpus-entry bound on the exported verdict table (`VerdictCache.
#: export`): the most-recently-used half of the cache bound — the publishing
#: job's own classes, not a long-lived replica's whole backlog.
EXPORT_MAX_ENTRIES = 1 << 15

#: Kill switch for A/B measurement (bench.py BENCH_SEMANTICS=1 legacy side)
#: and emergency rollback: SR_TPU_SEMANTICS=legacy disables the plane — every
#: verdict goes through the per-identity lru memo exactly as before this
#: module existed.
_enabled = os.environ.get("SR_TPU_SEMANTICS", "") != "legacy"


def set_enabled(flag: bool) -> bool:
    """Enable/disable the dedup-first plane (returns the previous setting).
    Disabling routes `is_consistent` back through the legacy
    `serialized_history` memo — used by the bench A/B and tests."""
    global _enabled
    prev = _enabled
    _enabled = bool(flag)
    return prev


def enabled() -> bool:
    return _enabled


class CanonForm:
    """A tester's canonical (label-free) form: threads reordered by content,
    prerequisite references remapped, both tester kinds normalized to one
    representation (sequential consistency = empty prerequisite sets)."""

    __slots__ = ("fp", "order", "perm", "history", "in_flight", "spec",
                 "linearizable", "n_ops", "rank")

    def __init__(self, fp, order, perm, history, in_flight, spec,
                 linearizable, n_ops):
        self.fp = fp  # 64-bit canonical fingerprint
        self.order = order  # canonical index -> original thread id
        self.perm = perm  # original thread id -> canonical index
        # history[t]: tuple of (prereqs, op, ret); prereqs: tuple of
        # (canonical peer index, min index), sorted.
        self.history = history
        self.in_flight = in_flight  # {canonical index: (prereqs, op)}
        self.spec = spec
        self.linearizable = linearizable
        self.n_ops = n_ops
        # Recording depth: strictly +1 per recorder call (on_invoke adds an
        # in-flight op; on_return converts in-flight -> completed, keeping
        # n_ops constant but raising completed count) — the sort key that
        # guarantees a parent class orders before its children.
        self.rank = sum(len(h) for h in history) + n_ops


#: Memoized "this tester cannot canonicalize" marker (user spec/ops without
#: a stable encoding — the legacy path handles those fine, the plane skips).
_UNSUPPORTED = object()


def _deterministic_invoke(spec) -> bool:
    """Whether the zero-search refutation rule ("an `on_return` child of a
    refuted parent is refuted") may be applied for `spec`. The rule's proof
    needs `invoke` to be deterministic AND `is_valid_step` to accept exactly
    the (ret, next-state) `invoke` produces — for a more permissive
    `is_valid_step` (a spec that validly accepts returns `invoke` would not
    pick), a child completing an op with an alternative recorded return can
    be serializable while the parent search, committed to `invoke`'s
    outcome, was not. A spec that does NOT override the base
    `SequentialSpec.is_valid_step` is deterministic by construction (the
    base derives it from `invoke` by equality); built-ins that override it
    for speed mirror `invoke` exactly and declare `invoke_deterministic =
    True`; anything else conservatively skips the rule (guidance falls back
    to validated candidates / the full search — slower, never wrong)."""
    declared = getattr(spec, "invoke_deterministic", None)
    if declared is not None:
        return bool(declared)
    from . import SequentialSpec

    return type(spec).is_valid_step is SequentialSpec.is_valid_step

#: Op/ret/spec payloads draw from tiny vocabularies (a model has a handful
#: of distinct Write/Read/ReadOk values), while canonicalization encodes
#: them once per tester — memoize the stable encodings so the hot path is a
#: dict hit, not a recursive byte walk. stable_encode outputs are
#: self-delimiting (type tag + length prefixes), so concatenations below
#: are unambiguous.
_ENC_MEMO: dict = {}
_ENC_MEMO_MAX = 1 << 16


def _enc(obj) -> bytes:
    try:
        got = _ENC_MEMO.get(obj)
    except TypeError:  # unhashable payload: encode without the memo
        return stable_encode(obj)
    if got is None:
        got = stable_encode(obj)
        if len(_ENC_MEMO) < _ENC_MEMO_MAX:
            _ENC_MEMO[obj] = got
    return got


def try_canonical_form(tester) -> Optional[CanonForm]:
    """`canonical_form`, degrading to None when the tester's spec, ops, or
    thread ids have no stable encoding — the plane is an optimization, so
    exotic user specs simply keep the legacy per-identity memo."""
    form = getattr(tester, "_canon", None)
    if form is _UNSUPPORTED:
        return None
    if form is not None:
        return form
    try:
        return canonical_form(tester)
    except TypeError:
        try:
            tester._canon = _UNSUPPORTED
        except AttributeError:
            pass
        return None


def canonical_form(tester) -> CanonForm:
    """Compute (and memoize on the tester — testers are immutable) the
    canonical form. Linear in history size plus one sort over threads.
    Raises TypeError when something in the history has no stable encoding
    (use `try_canonical_form` on untrusted testers)."""
    form = getattr(tester, "_canon", None)
    if form is not None and form is not _UNSUPPORTED:
        return form
    # EXACT types only, not a name check or isinstance: a user subclass may
    # override the search semantics (and a name check would misclassify it
    # into the 2-tuple unpack below and crash) — unknown tester classes keep
    # the legacy per-identity memo via try_canonical_form's TypeError path.
    # (Lazy imports: both modules import this one at module level.)
    from .linearizability import LinearizabilityTester
    from .sequential_consistency import SequentialConsistencyTester

    if type(tester) is LinearizabilityTester:
        linearizable = True
    elif type(tester) is SequentialConsistencyTester:
        linearizable = False
    else:
        raise TypeError(
            f"unsupported tester class for the canonical plane: "
            f"{type(tester).__name__}"
        )
    hist = tester.history_by_thread
    ifl = tester.in_flight_by_thread

    # Round 0: label-free per-thread signatures (ops/rets + in-flight op,
    # prerequisite references dropped — they mention peer labels). Built
    # from memoized per-payload encodings; stable_encode outputs are
    # self-delimiting, so the joins cannot collide across boundaries.
    sig0: dict = {}
    for tid, entries in hist.items():
        # The entry count anchors pair parsing: the joined per-payload
        # encodings can never be re-segmented into a different history.
        parts = [b"h%d:" % len(entries)]
        if linearizable:
            for _lc, op, ret in entries:
                parts.append(_enc(op))
                parts.append(_enc(ret))
        else:
            for op, ret in entries:
                parts.append(_enc(op))
                parts.append(_enc(ret))
        if tid in ifl:
            f = ifl[tid]
            parts.append(b"I")
            parts.append(_enc(f[1] if linearizable else f))
        sig0[tid] = b"".join(parts)
    for tid in ifl:  # an in-flight-only thread not yet in history (defensive)
        if tid not in sig0:
            f = ifl[tid]
            sig0[tid] = b"h0:I" + _enc(f[1] if linearizable else f)

    # Round 1: refine with prerequisite structure expressed through peers'
    # round-0 signatures (label-free). Sequential consistency has none, so
    # sig1 == sig0 there.
    if linearizable:
        def prereq_sig(last_completed):
            return b"".join(
                b"%s@%d;" % (sig0.get(peer, b""), idx)
                for peer, idx in sorted(
                    last_completed,
                    key=lambda pi: (sig0.get(pi[0], b""), pi[1]),
                )
            )

        sig1: dict = {}
        for tid in sig0:
            ps = [sig0[tid]]
            for entry in hist.get(tid, ()):
                ps.append(b"|")
                ps.append(prereq_sig(entry[0]))
            if tid in ifl:
                ps.append(b"!")
                ps.append(prereq_sig(ifl[tid][0]))
            sig1[tid] = b"".join(ps)
    else:
        sig1 = sig0

    # Canonical order: (refined signature, round-0 signature), ties broken by
    # the original label's stable encoding — only truly symmetric threads
    # (identical full content) can tie through both rounds, and for those any
    # assignment yields the same canonical encoding.
    order = sorted(sig0, key=lambda t: (sig1[t], sig0[t], _enc(t)))
    perm = {tid: i for i, tid in enumerate(order)}

    def remap(last_completed):
        return tuple(sorted((perm[p], int(i)) for p, i in last_completed))

    # One pass builds BOTH the canonical structure (what the search and
    # witness validation consume) and its digest input (per-thread round-0
    # bytes + remapped prerequisite references — together a complete
    # description of the relabeled tester).
    digest = [b"T", _enc(type(tester).__name__), _enc(tester.init_ref_obj)]
    c_hist = []
    n_ops = 0
    for tid in order:
        rows = []
        digest.append(b"t")
        digest.append(sig0[tid])
        for entry in hist.get(tid, ()):
            if linearizable:
                lc, op, ret = entry
                rlc = remap(lc)
                rows.append((rlc, op, ret))
                digest.append(
                    b"p" + b"".join(b"%d@%d;" % pi for pi in rlc)
                )
            else:
                op, ret = entry
                rows.append(((), op, ret))
        n_ops += len(rows)
        c_hist.append(tuple(rows))
    c_ifl = {}
    for tid in order:
        if tid in ifl:
            if linearizable:
                lc, op = ifl[tid]
                rlc = remap(lc)
                c_ifl[perm[tid]] = (rlc, op)
                digest.append(
                    b"i%d" % perm[tid]
                    + b"".join(b"%d@%d;" % pi for pi in rlc)
                )
            else:
                c_ifl[perm[tid]] = ((), ifl[tid])
                digest.append(b"i%d;" % perm[tid])
            n_ops += 1

    fp = fingerprint_bytes(b"".join(digest))
    form = CanonForm(fp, tuple(order), perm, tuple(c_hist), c_ifl,
                     tester.init_ref_obj, linearizable, n_ops)
    try:
        tester._canon = form
    except AttributeError:
        pass  # __slots__-less exotic subclass: recompute next time
    return form


# -- canonical-space search ----------------------------------------------------


#: The canonical plane's native-search gate: every plane search runs at most
#: once per equivalence class (then lives in the cache and the corpus), so
#: the ctypes marshalling amortizes far below the legacy per-call crossover
#: (NATIVE_MIN_OPS=12). 5+ ops is where the C search reliably beats the
#: Python one including marshalling.
PLANE_NATIVE_MIN_OPS = 5


def search_steps(form: CanonForm):
    """The full backtracking search in canonical space, returning the witness
    as ((thread, from_in_flight), ...) steps or None. Deterministic: threads
    are visited in canonical order (dict insertion order below), so the same
    equivalence class yields the same steps in every process — which is what
    lets the corpus replay verdicts bit-identically. Tries the native
    serializer first (it visits interleavings in the same order as the
    Python search)."""
    from ._native_bridge import NOT_SUPPORTED, native_serialize_steps

    T = len(form.history)
    if form.linearizable:
        hist = {t: tuple((lc, op, ret) for lc, op, ret in form.history[t])
                for t in range(T)}
        ifl = dict(form.in_flight)
    else:
        hist = {t: tuple((op, ret) for _lc, op, ret in form.history[t])
                for t in range(T)}
        ifl = {t: op for t, (_lc, op) in form.in_flight.items()}
    native = native_serialize_steps(
        form.spec, hist, ifl, linearizable=form.linearizable,
        min_ops=PLANE_NATIVE_MIN_OPS,
    )
    if native is not NOT_SUPPORTED:
        return None if native is None else tuple(native)

    remaining = {t: tuple(enumerate(form.history[t])) for t in range(T)}
    out = _serialize_steps([], form.spec, remaining, form.in_flight)
    return None if out is None else tuple(out)


def _violates(prereqs, remaining) -> bool:
    for peer, min_idx in prereqs:
        ops = remaining.get(peer)
        if ops and ops[0][0] <= min_idx:
            return True
    return False


def _serialize_steps(steps, ref_obj, remaining, in_flight):
    """`linearizability._serialize` on the unified canonical representation,
    recording (thread, from_in_flight) steps instead of (op, ret) pairs.
    Visits interleavings in the identical order."""
    if all(not h for h in remaining.values()):
        return steps
    for t in remaining:
        history = remaining[t]
        if not history:
            if t not in in_flight:
                continue
            prereqs, op = in_flight[t]
            if _violates(prereqs, remaining):
                continue
            _ret, next_obj = ref_obj.invoke(op)
            next_ifl = {u: v for u, v in in_flight.items() if u != t}
            result = _serialize_steps(
                steps + [(t, True)], next_obj, remaining, next_ifl
            )
            if result is not None:
                return result
        else:
            (_idx, (prereqs, op, ret)) = history[0]
            next_remaining = dict(remaining)
            next_remaining[t] = history[1:]
            if _violates(prereqs, next_remaining):
                continue
            next_obj = ref_obj.is_valid_step(op, ret)
            if next_obj is None:
                continue
            result = _serialize_steps(
                steps + [(t, False)], next_obj, next_remaining, in_flight
            )
            if result is not None:
                return result
    return None


def validate_steps(form: CanonForm, steps) -> bool:
    """O(n) check that `steps` is a valid serialization of `form`: per-thread
    order, real-time prerequisites, spec validity, and completeness of
    completed ops (in-flight steps are optional). Witness guidance NEVER
    trusts a candidate without this."""
    T = len(form.history)
    next_idx = [0] * T
    used_ifl = set()
    spec = form.spec
    for step in steps:
        t, from_ifl = step
        if not 0 <= t < T:
            return False
        if from_ifl:
            ent = form.in_flight.get(t)
            if ent is None or t in used_ifl:
                return False
            if next_idx[t] < len(form.history[t]):
                # An in-flight op serializes only after every completed op of
                # its own thread (single outstanding op per thread).
                return False
            prereqs, op = ent
            for peer, min_idx in prereqs:
                if peer != t and next_idx[peer] <= min_idx:
                    return False
            _ret, spec = spec.invoke(op)
            used_ifl.add(t)
        else:
            if next_idx[t] >= len(form.history[t]):
                return False
            prereqs, op, ret = form.history[t][next_idx[t]]
            next_idx[t] += 1
            for peer, min_idx in prereqs:
                if peer != t and next_idx[peer] <= min_idx:
                    return False
            spec = spec.is_valid_step(op, ret)
            if spec is None:
                return False
    return all(next_idx[t] == len(form.history[t]) for t in range(T))


# -- the verdict cache ---------------------------------------------------------


class VerdictCache:
    """Bounded LRU of canonical fingerprint -> (verdict, witness steps).
    Witness steps are None for False verdicts and for verdicts preloaded
    from a corpus table (the bit is universally valid; the witness is a
    local acceleration)."""

    def __init__(self, max_entries: int = CACHE_MAX_ENTRIES):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self.counters = {
            "canonical_hits": 0,
            "canonical_misses": 0,
            "canonical_collapsed": 0,
            "witness_guided_hits": 0,
            "witness_guided_misses": 0,
            "full_searches": 0,
            "batch_evals": 0,
            "batch_states": 0,
            "batch_parallel_evals": 0,
            "batch_eval_ms_total": 0.0,
            "batch_eval_ms_last": 0.0,
            "preloaded_verdicts": 0,
            "exported_verdicts": 0,
            "trims": 0,
            "trimmed_entries": 0,
            "legacy_clears": 0,
        }

    def _count(self, key: str, n=1) -> None:
        with self._lock:
            self.counters[key] += n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, fp: int):
        with self._lock:
            ent = self._entries.get(fp)
            if ent is not None:
                self._entries.move_to_end(fp)
            return ent

    def put(self, fp: int, verdict: bool, steps) -> None:
        with self._lock:
            self._entries[fp] = (bool(verdict), steps)
            self._entries.move_to_end(fp)
            if len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def preload(self, fps, verdicts) -> int:
        """Insert (fingerprint, verdict-bit) pairs from a packed corpus
        table. Existing entries win (they may carry a witness). Returns the
        number of NEW entries."""
        new = 0
        with self._lock:
            for fp, bit in zip(fps, verdicts):
                fp = int(fp)
                if fp not in self._entries:
                    self._entries[fp] = (bool(bit), None)
                    new += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            self.counters["preloaded_verdicts"] += new
        return new

    def export(self, max_entries: Optional[int] = None):
        """The packed (canonical fingerprint, verdict bit) table — the corpus
        payload. Verdicts are content-addressed by canonical class, so the
        table is universally valid regardless of which job computed it.
        Bounded to the `max_entries` (default EXPORT_MAX_ENTRIES) most
        recently USED entries: gets refresh recency, so the publishing job's
        own classes sit at the LRU tail — the bound keeps a long-lived
        replica's unrelated backlog from inflating every published entry
        while over-including at most the hot set (harmless: class-addressed
        bits can only be unused, never wrong)."""
        import numpy as np

        if max_entries is None:
            max_entries = EXPORT_MAX_ENTRIES
        with self._lock:
            items = list(self._entries.items())[-max_entries:]
            self.counters["exported_verdicts"] += len(items)
        fps = np.asarray([fp for fp, _ in items], dtype=np.uint64)
        bits = np.asarray([v for _, (v, _s) in items], dtype=np.uint8)
        return fps, bits

    def trim(self, max_entries: Optional[int] = None) -> int:
        """Shrink to `max_entries` (default: half the bound), oldest first.
        Called at service job finalize so long-lived replicas stay bounded.
        Returns entries dropped."""
        target = self.max_entries // 2 if max_entries is None else max_entries
        dropped = 0
        with self._lock:
            while len(self._entries) > target:
                self._entries.popitem(last=False)
                dropped += 1
            if dropped:
                self.counters["trims"] += 1
                self.counters["trimmed_entries"] += dropped
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["canonical_entries"] = len(self._entries)
        return out


#: THE process-global verdict cache (both tester kinds; the kind is folded
#: into the canonical fingerprint). Exported through the obs REGISTRY
#: "semantics" source (see linearizability.verdict_cache_stats).
CACHE = VerdictCache()


# -- verdict evaluation --------------------------------------------------------


#: Per-thread verdict-plane consultation counter — the feedback signal for
#: the checkers' block-prefetch gate. Thread-local on purpose: a
#: process-global counter would be moved by sibling worker threads and the
#: gate could never observe "this thread's block consumed nothing".
_TLS = threading.local()


def local_consultations() -> int:
    return getattr(_TLS, "consultations", 0)


def _consulted() -> None:
    _TLS.consultations = getattr(_TLS, "consultations", 0) + 1


def _seal(tester) -> None:
    """Sever a tester's recording uplink once its class verdict is cached:
    guidance FROM it reads the cache entry, never the chain, so keeping the
    `_parent` reference would only pin the whole ancestry (O(depth) tester
    objects per live history) for the lifetime of every retained state —
    exactly the long-lived-service growth the cache bounds exist to stop.
    Children that recorded off this tester keep their own one-hop parent
    reference; chains collapse to <= 2 links as verdicts resolve."""
    try:
        tester._parent = None
        tester._delta = None
    except AttributeError:
        pass


def probe_verdict(tester) -> Optional[bool]:
    """Cache probe + witness guidance, NO full search. Returns the verdict
    when the plane can decide cheaply, else None. Used by the legacy
    `serialized_history` path so a direct call never pays a search it
    wouldn't have before."""
    if not _enabled or not tester.is_valid_history:
        return None
    _consulted()
    form = try_canonical_form(tester)
    if form is None:
        return None
    ent = CACHE.get(form.fp)
    if ent is not None:
        CACHE._count("canonical_hits")
        _seal(tester)
        return ent[0]
    guided = _witness_guided(tester, form)
    if guided is None:
        guided = _guided_via_ancestors(tester, form)
    if guided is not None:
        verdict, steps = guided
        CACHE.put(form.fp, verdict, steps)
        CACHE._count("witness_guided_hits")
        _seal(tester)
        return verdict
    return None


#: How far up the recording chain `_guided_via_ancestors` may climb. One
#: checker transition can record several ops (a delivery records the return
#: AND each emission's invocation), so the direct parent of a state's tester
#: is often an uncached intermediate; chains longer than this are rare and
#: fall through to the full search.
ANCESTOR_BUDGET = 16


def _guided_via_ancestors(tester, form: CanonForm):
    """When the direct parent is uncached, climb the recording chain to the
    nearest cached ancestor and guide FORWARD hop by hop, caching every
    intermediate — so multi-recording transitions (deliver = return +
    invocations) still resolve without a full search."""
    chain = [(tester, form)]
    cur = tester
    found = False
    while len(chain) <= ANCESTOR_BUDGET:
        parent = getattr(cur, "_parent", None)
        if (
            parent is None
            or getattr(cur, "_delta", None) is None
            or not parent.is_valid_history
        ):
            return None
        p_form = try_canonical_form(parent)
        if p_form is None:
            return None
        if CACHE.get(p_form.fp) is not None:
            found = True
            break
        chain.append((parent, p_form))
        cur = parent
    if not found:
        return None
    got = None
    for t, f in reversed(chain):
        got = _witness_guided(t, f)
        if got is None:
            return None  # guidance broke mid-chain: full search decides
        CACHE.put(f.fp, got[0], got[1])
        _seal(t)
        if t is not tester:
            CACHE._count("witness_guided_hits")
    return got


#: `probe_cached_negative` engages only at/above this history size (or when
#: the canonical form is already memoized): a sub-6-op legacy search runs in
#: ~10us, below the cost of canonicalizing the tester.
PROBE_MIN_OPS = 6


def probe_cached_negative(tester) -> bool:
    """True iff the plane already KNOWS the class is not serializable — the
    only fact `serialized_history()` can use (a positive verdict still runs
    the legacy search for the exact legacy witness, so spending witness
    guidance there would be pure overhead). Checks the cache plus the one
    zero-validation refutation rule: an `on_return` child of a refuted
    parent is refuted (see the module docstring)."""
    if not _enabled or not tester.is_valid_history:
        return False
    _consulted()
    # Below this size the legacy search costs less than canonicalization —
    # don't tax micro-histories unless the canonical form already exists
    # (an `is_consistent`/batch caller computed it; probing is then free).
    if len(tester) < PROBE_MIN_OPS and getattr(tester, "_canon", None) is None:
        return False
    form = try_canonical_form(tester)
    if form is None:
        return False
    ent = CACHE.get(form.fp)
    if ent is not None:
        if not ent[0]:
            CACHE._count("canonical_hits")
        _seal(tester)
        return not ent[0]
    parent = getattr(tester, "_parent", None)
    delta = getattr(tester, "_delta", None)
    if (
        parent is not None
        and delta is not None
        and delta[0] == "ret"
        and parent.is_valid_history
        and _deterministic_invoke(form.spec)
    ):
        p_form = try_canonical_form(parent)
        if p_form is not None:
            p_ent = CACHE.get(p_form.fp)
            if p_ent is not None and not p_ent[0]:
                CACHE.put(form.fp, False, None)
                CACHE._count("witness_guided_hits")
                _seal(tester)
                return True
    return False


def verdict(tester) -> bool:
    """The dedup-first verdict: canonical cache -> witness guidance -> full
    canonical search. Boolean-identical to `serialized_history() is not
    None` by construction."""
    if not tester.is_valid_history:
        return False
    if not _enabled:
        return tester.serialized_history() is not None
    form = try_canonical_form(tester)
    if form is None:
        return tester.serialized_history() is not None
    got = probe_verdict(tester)
    if got is not None:
        return got
    CACHE._count("canonical_misses")
    if getattr(tester, "_parent", None) is not None:
        CACHE._count("witness_guided_misses")
    steps = search_steps(form)
    CACHE._count("full_searches")
    CACHE.put(form.fp, steps is not None, steps)
    _seal(tester)
    return steps is not None


def note_verdict(tester, is_serializable: bool) -> None:
    """Opportunistic cache insert from a legacy search result (no witness).
    Lets direct `serialized_history` callers feed the plane for free."""
    if not _enabled or not tester.is_valid_history:
        return
    form = try_canonical_form(tester)
    if form is not None:
        if CACHE.get(form.fp) is None:
            CACHE.put(form.fp, is_serializable, None)
        _seal(tester)


def _witness_guided(tester, form: CanonForm):
    """Try to decide the tester from its parent's cached verdict. Returns
    (verdict, steps-or-None) or None when guidance doesn't apply. Every
    positive answer is either a validated witness or a propagation rule
    proved in the module docstring."""
    parent = getattr(tester, "_parent", None)
    delta = getattr(tester, "_delta", None)
    if parent is None or delta is None or not parent.is_valid_history:
        return None
    p_form = try_canonical_form(parent)
    if p_form is None:
        return None
    p_ent = CACHE.get(p_form.fp)
    if p_ent is None:
        return None  # parent unknown: no recursion, fall through to search
    p_verdict, p_steps = p_ent
    kind, tid = delta

    if kind == "inv":
        # Parent serializable => child serializable (in-flight ops are
        # optional; the parent's witness is the child's verbatim).
        if p_verdict:
            if p_steps is None:
                return True, None
            steps = _map_steps(p_steps, p_form, form)
            if steps is not None and validate_steps(form, steps):
                return True, steps
            return True, None  # propagation holds even without the witness
        return None  # parent False: the new in-flight op may rescue it

    # kind == "ret": the child completed thread `tid`'s in-flight op.
    if not p_verdict:
        # Any child serialization would be a parent serialization — but ONLY
        # when the spec's is_valid_step accepts exactly what invoke produces
        # (_deterministic_invoke); otherwise the child's recorded return may
        # be serializable where invoke's outcome was not, so fall through to
        # the full search.
        if _deterministic_invoke(p_form.spec):
            return False, None
        return None
    if p_steps is None:
        return None
    base = _map_steps(p_steps, p_form, form)
    if base is None:
        return None
    ct = form.perm.get(tid)
    if ct is None:
        return None
    # Candidate 1: the parent witness already took the in-flight op's effect
    # — the same position now consumes the completed entry.
    flipped = tuple(
        (t, False) if (t == ct and fl) else (t, fl) for t, fl in base
    )
    if flipped != base and validate_steps(form, flipped):
        return True, flipped
    # Candidates 2..n+2: insert the completed step at each position, tail
    # first (real-time order usually forces a fresh completion late).
    without = tuple(s for s in base if s != (ct, True))
    for pos in range(len(without), -1, -1):
        cand = without[:pos] + ((ct, False),) + without[pos:]
        if validate_steps(form, cand):
            return True, cand
    return None


def _map_steps(steps, src: CanonForm, dst: CanonForm):
    """Relabel witness steps from the parent's canonical space to the
    child's (parent canonical -> original -> child canonical)."""
    out = []
    for t, fl in steps:
        if not 0 <= t < len(src.order):
            return None
        ct = dst.perm.get(src.order[t])
        if ct is None:
            return None
        out.append((ct, fl))
    return tuple(out)


def serialized_from_steps(tester, steps):
    """Reconstruct the (op, ret) witness list for `tester` from canonical
    steps — used by tests to assert witness validity, and by any consumer
    that wants a concrete order out of the canonical plane."""
    form = canonical_form(tester)
    if not validate_steps(form, steps):
        return None
    next_idx = [0] * len(form.history)
    spec = form.spec
    out = []
    for t, from_ifl in steps:
        if from_ifl:
            _prereqs, op = form.in_flight[t]
            ret, spec = spec.invoke(op)
        else:
            _prereqs, op, ret = form.history[t][next_idx[t]]
            next_idx[t] += 1
            spec = spec.is_valid_step(op, ret)
        out.append((op, ret))
    return out


def cached_steps(tester):
    """The cached canonical witness for `tester`'s class, or None."""
    if not tester.is_valid_history:
        return None
    form = try_canonical_form(tester)
    if form is None:
        return None
    ent = CACHE.get(form.fp)
    return None if ent is None else ent[1]
