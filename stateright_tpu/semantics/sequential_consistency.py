"""Sequential-consistency tester (ref: src/semantics/sequential_consistency.rs).

Like `LinearizabilityTester` but without real-time constraints: a total order
need only respect each thread's own operation order plus the spec's semantics,
so e.g. a thread may observe stale state relative to another thread's completed
operation.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

from . import ConsistencyTester, SequentialSpec
from .canonical import enabled as _plane_enabled


class SequentialConsistencyTester(ConsistencyTester):
    __slots__ = (
        "init_ref_obj",
        "history_by_thread",
        "in_flight_by_thread",
        "is_valid_history",
        "_key_cache",  # lazy identity-tuple cache (testers are immutable)
        "_hash",
        # Dedup-first verdict plane hints (see LinearizabilityTester):
        "_canon",
        "_parent",
        "_delta",
    )

    def __init__(
        self,
        init_ref_obj: SequentialSpec,
        history_by_thread: Optional[dict] = None,
        in_flight_by_thread: Optional[dict] = None,
        is_valid_history: bool = True,
    ):
        self.init_ref_obj = init_ref_obj
        self.history_by_thread = history_by_thread or {}  # {tid: ((op, ret), ...)}
        self.in_flight_by_thread = in_flight_by_thread or {}  # {tid: op}
        self.is_valid_history = is_valid_history

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    # -- recording (ref: sequential_consistency.rs:97-143) ---------------------

    def on_invoke(self, thread_id, op) -> "SequentialConsistencyTester":
        if not self.is_valid_history or thread_id in self.in_flight_by_thread:
            return self._invalidated()
        in_flight = dict(self.in_flight_by_thread)
        in_flight[thread_id] = op
        history = dict(self.history_by_thread)
        history.setdefault(thread_id, ())
        child = SequentialConsistencyTester(
            self.init_ref_obj, history, in_flight, True
        )
        # Plane-gated witness-guidance hints — see LinearizabilityTester.
        if _plane_enabled():
            child._parent = self
            child._delta = ("inv", thread_id)
        return child

    def on_return(self, thread_id, ret) -> "SequentialConsistencyTester":
        if not self.is_valid_history or thread_id not in self.in_flight_by_thread:
            return self._invalidated()
        in_flight = dict(self.in_flight_by_thread)
        op = in_flight.pop(thread_id)
        history = dict(self.history_by_thread)
        history[thread_id] = history.get(thread_id, ()) + ((op, ret),)
        child = SequentialConsistencyTester(
            self.init_ref_obj, history, in_flight, True
        )
        if _plane_enabled():
            child._parent = self
            child._delta = ("ret", thread_id)
        return child

    def _invalidated(self) -> "SequentialConsistencyTester":
        return SequentialConsistencyTester(
            self.init_ref_obj,
            self.history_by_thread,
            self.in_flight_by_thread,
            False,
        )

    def is_consistent(self) -> bool:
        """Dedup-first verdict path — see LinearizabilityTester.is_consistent."""
        from .canonical import verdict

        return verdict(self)

    # -- serialization search (ref: sequential_consistency.rs:152-238) ---------

    def serialized_history(self) -> Optional[list]:
        if not self.is_valid_history:
            return None
        from .canonical import probe_cached_negative

        if probe_cached_negative(self):
            return None
        cached = _serialized_cached(self)
        return None if cached is None else list(cached)

    def _serialized_uncached(self) -> Optional[list]:
        from ._native_bridge import NOT_SUPPORTED, native_serialized_history

        native = native_serialized_history(
            self.init_ref_obj,
            self.history_by_thread,
            self.in_flight_by_thread,
            linearizable=False,
        )
        if native is not NOT_SUPPORTED:
            return native
        return _serialize(
            [],
            self.init_ref_obj,
            dict(self.history_by_thread),
            self.in_flight_by_thread,
        )

    # -- identity --------------------------------------------------------------

    def _key(self):
        # Lazy identity-tuple memo, ported from LinearizabilityTester._key
        # (round-4 exact-closure profile): testers are immutable, so the two
        # frozensets are built ONCE instead of on every hash/eq — `hid_of`
        # dict probes during lowering closures dominate otherwise.
        k = getattr(self, "_key_cache", None)
        if k is None:
            k = self._key_cache = (
                self.init_ref_obj,
                frozenset(self.history_by_thread.items()),
                frozenset(self.in_flight_by_thread.items()),
                self.is_valid_history,
            )
        return k

    def __stable_encode__(self):
        return (
            type(self).__name__,
            self.init_ref_obj,
            self.history_by_thread,
            self.in_flight_by_thread,
            self.is_valid_history,
        )

    def __eq__(self, other) -> bool:
        return isinstance(other, type(self)) and self._key() == other._key()

    def __hash__(self) -> int:
        h = getattr(self, "_hash", None)
        if h is None:
            h = self._hash = hash(self._key())
        return h

    def __repr__(self) -> str:
        return (
            f"SequentialConsistencyTester(history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r}, valid={self.is_valid_history})"
        )


@lru_cache(maxsize=1 << 15)
def _serialized_cached(tester: "SequentialConsistencyTester"):
    """Memoized search result on the immutable tester (equal histories recur
    across many checker states)."""
    result = tester._serialized_uncached()
    if result is None:
        # Negatives only — see linearizability._serialized_cached.
        from .canonical import note_verdict

        note_verdict(tester, False)
        return None
    return tuple(result)


def _serialize(valid_history, ref_obj, remaining, in_flight) -> Optional[list]:
    if all(not h for h in remaining.values()):
        return valid_history
    for thread_id in remaining:
        history = remaining[thread_id]
        if not history:
            if thread_id not in in_flight:
                continue
            op = in_flight[thread_id]
            ret, next_obj = ref_obj.invoke(op)
            next_in_flight = {t: v for t, v in in_flight.items() if t != thread_id}
            result = _serialize(
                valid_history + [(op, ret)], next_obj, remaining, next_in_flight
            )
            if result is not None:
                return result
        else:
            op, ret = history[0]
            next_obj = ref_obj.is_valid_step(op, ret)
            if next_obj is None:
                continue
            next_remaining = dict(remaining)
            next_remaining[thread_id] = history[1:]
            result = _serialize(
                valid_history + [(op, ret)], next_obj, next_remaining, in_flight
            )
            if result is not None:
                return result
    return None
