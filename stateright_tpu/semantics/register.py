"""Register reference objects (ref: src/semantics/register.rs,
src/semantics/write_once_register.rs)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

from . import SequentialSpec


# -- operations / returns (shared by Register and WORegister) ------------------


@dataclass(frozen=True)
class Write:
    value: Any

    def __repr__(self):
        return f"Write({self.value!r})"


@dataclass(frozen=True)
class Read:
    def __repr__(self):
        return "Read"


@dataclass(frozen=True)
class WriteOk:
    def __repr__(self):
        return "WriteOk"


@dataclass(frozen=True)
class WriteFail:
    def __repr__(self):
        return "WriteFail"


@dataclass(frozen=True)
class ReadOk:
    value: Any

    def __repr__(self):
        return f"ReadOk({self.value!r})"


@dataclass(frozen=True)
class Register(SequentialSpec):
    """A read/write register (ref: src/semantics/register.rs:8-49)."""

    value: Any = None

    #: `is_valid_step` below mirrors `invoke` exactly (speed-only override),
    #: so the canonical plane's zero-search refutation rule applies
    #: (semantics/canonical.py `_deterministic_invoke`).
    invoke_deterministic = True

    def invoke(self, op) -> Tuple[Any, "Register"]:
        if isinstance(op, Write):
            return WriteOk(), Register(op.value)
        if isinstance(op, Read):
            return ReadOk(self.value), self
        raise TypeError(f"not a register op: {op!r}")

    def is_valid_step(self, op, ret) -> Optional["Register"]:
        if isinstance(op, Write) and ret == WriteOk():
            return Register(op.value)
        if isinstance(op, Read) and isinstance(ret, ReadOk):
            return self if ret.value == self.value else None
        return None


@dataclass(frozen=True)
class WORegister(SequentialSpec):
    """A write-once register: the first write wins; later writes of a different
    value fail, equal values succeed (ref: src/semantics/write_once_register.rs).
    `value` uses a sentinel for "unwritten" so None is a writable value."""

    value: Any = None
    written: bool = False

    #: Speed-only `is_valid_step` override mirroring `invoke` exactly — see
    #: Register.invoke_deterministic.
    invoke_deterministic = True

    def invoke(self, op) -> Tuple[Any, "WORegister"]:
        if isinstance(op, Write):
            if not self.written:
                return WriteOk(), WORegister(op.value, True)
            if op.value == self.value:
                return WriteOk(), self
            return WriteFail(), self
        if isinstance(op, Read):
            return ReadOk(self.value if self.written else None), self
        raise TypeError(f"not a register op: {op!r}")

    def is_valid_step(self, op, ret) -> Optional["WORegister"]:
        if isinstance(op, Write):
            if ret == WriteOk():
                if not self.written:
                    return WORegister(op.value, True)
                return self if op.value == self.value else None
            if ret == WriteFail():
                return self if self.written and op.value != self.value else None
            return None
        if isinstance(op, Read) and isinstance(ret, ReadOk):
            expected = self.value if self.written else None
            return self if ret.value == expected else None
        return None
