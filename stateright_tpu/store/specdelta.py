"""Definition-delta salvage: the Spec-CI subsystem (ROADMAP item 4, the
"one-bit model edit" residue).

The corpus content key hashes the WHOLE model definition, so editing one
property's condition changes the key, the family hash, and every warm rung
— a one-line spec edit re-explores the state space from scratch. This
module factors the definition hash into PER-COMPONENT digests, classifies
the edit between a new model and a published entry, and implements the
sound salvage rules behind the ``"delta"`` rung of `knobs.WARM_KINDS`:

- `def_components(model)`: one digest per definition component —
  ``geometry`` (jax version x payload format x class name x lane/action
  shape), ``init`` (concrete init-state bytes), ``expand`` /
  ``boundary`` / ``repr`` (abstract jaxprs), and ``props`` (one digest
  per property over its name, expectation, and condition jaxpr). The
  joint definition hash (`corpus.model_def_hash`) is DERIVED from these
  digests, so the factoring and the key can never drift apart.
- `classify(new, old)`: name the edit class between two component
  vectors — ``identical`` | ``properties-only`` | ``boundary-only`` |
  ``expand/init`` (the unsalvageable class, which also absorbs missing
  or pre-delta component records: never misclassify, degrade to cold).
- `salvage_properties` / `salvage_boundary`: build the entry a delta
  warm-start may serve, or refuse (return None).

Soundness arguments (proved from the factored key)
--------------------------------------------------

**Properties-only** (``geometry``/``init``/``expand``/``boundary``/
``repr`` digests all equal; only ``props`` differ). The engines' visited
set, claim/pop order, generation counts, and depths are functions of the
init states, the expand kernel, the boundary, the symmetry
representative, and the batch size alone — properties only OBSERVE
popped states. A published COMPLETE entry was, by the publish gate
(scheduler.prepare_publish), a full-exhaustion run: never early-exited,
so its traversal never depended on its property verdicts either. Under
an equal batch size and an equal finish signature, a cold run of the
edited model therefore pops the SAME states in the SAME order — its
counts replay verbatim, and only the verdict plane must be recomputed:
unchanged properties (equal per-property digest => identical condition
jaxpr => identical verdict on every state) replay their recorded first
witness; changed/added properties are re-evaluated over the entry's
recorded journal-state plane (`journal_states`, exactly the claimed
rows in pop order, with `journal_depths` reproducing the
target_max_depth eval mask). Two refusals keep this exact: a
changed/added EVENTUALLY property needs the pending-bit/terminality
plane the entry does not record, and a re-evaluated discovery set that
SATISFIES the run's finish policy means the cold run would have
early-exited mid-stream with smaller counts (discovery sets grow
monotonically and every finish kind is monotone in them, so "the final
set does not satisfy" proves "no prefix did" — full exhaustion is then
the cold behavior too).

**Boundary-only** (only the ``boundary`` digest differs). Let V be the
entry's visited set and B_old/B_new the two boundary predicates. The
engines apply the boundary when a successor is CLAIMED (an
out-of-boundary successor is never inserted, journaled, or queued), so
V contains only B_old-true states and B_old's values on the successors
the old run declined — exactly the states a wider predicate would
admit — are UNOBSERVABLE from the entry. No boundary edit is therefore
provably vacuous from recorded planes; the one sound salvage is a
re-expansion continuation, gated by two checks evaluated on what IS
recorded:

- *Prefix validity*: B_new must hold on EVERY row of V (one False row
  means a visited state is excluded under the edit — V
  over-approximates Reach_new — refuse). The served prefix is then
  exactly the ISSUE's "states inside both boundaries": all of V.
- *Root coverage*: every init state B_new admits must already be in V
  (a formerly-excluded init would root a subtree no continuation from
  V's rows can reach — refuse).

Under both, V is a subset of Reach_new (each V-path runs through
B_new-true states), and re-expanding ALL of V as the continuation
frontier explores exactly Reach_new: for any reachable x not in V, the
last state of x's path inside V is re-expanded and claims the next hop
(induction). Every state of Reach_new is popped exactly once (V rows
are pushed once each; new states claim once through the preloaded
table), so with the baseline ``state_count`` RESET to the raw
B_new-admitted init count — the re-expansion re-counts every pop, the
prefix's own generation tally must not double in — state_count and
unique_count at full exhaustion equal a cold run's exactly.
Traversal-order statistics (max_depth — the re-pushed rows keep their
OLD claim depths and a widened space can shorten paths — and witness
fingerprints) may differ from a cold BFS, and a finish policy that
fires MID-continuation stops at an order-dependent point; so the
continuation never publishes (no_publish), refuses depth/count targets
and EVENTUALLY properties (the pending-bit plane for re-pushed rows is
not recorded), refuses when the prefix's discoveries already satisfy
the finish policy, and documents that counts are cold-exact only at
full exhaustion — discoveries and verdicts are correct always.

**expand/init** (any other difference, including a missing/corrupt/
pre-delta component record): no subset of V is provably reachable under
the edited kernel — refuse explicitly; the refusal is counted
(`delta_refusals`) and the run is cold, bit-identical to never-warmed.

Deliberately jax-free at import time (store/warm.py and knobs.py probe
jax-free): jaxpr tracing and batched evaluation import lazily.
"""

from __future__ import annotations

import dataclasses
import hashlib
import weakref
from typing import Optional

import numpy as np

from ..core.model import Expectation

__all__ = [
    "DELTA_CLASSES",
    "def_components",
    "joint_def_hash",
    "spec_core_hash",
    "classify",
    "component_reuse",
    "salvage_properties",
    "salvage_boundary",
    "eval_boundary",
]

#: The delta-classifier vocabulary, best case first. "identical" never
#: reaches the delta rung (equal components => equal definition hash =>
#: the exact/near family already served); "expand/init" is the explicit
#: refusal class.
DELTA_CLASSES = (
    "identical", "properties-only", "boundary-only", "expand/init",
)

#: The component names every well-formed vector carries. "props" is a
#: {property name: digest} sub-dict; "repr" is "" for symmetry-less models.
_CORE_PARTS = ("geometry", "init", "expand", "boundary", "repr")

#: Per-model component-vector cache, keyed by id() with a weakref death
#: callback (models override __eq__ without __hash__, so a
#: WeakKeyDictionary cannot hold them): tracing jaxprs costs milliseconds
#: and the service traces per submission; caching never keeps a model
#: alive and a recycled id can never serve a stale vector (the liveness
#: check compares the referent by identity).
_COMPONENT_CACHE: dict = {}

#: Batched host evaluation chunk for boundary/condition re-evaluation.
_EVAL_BATCH = 4096


def _digest(*parts) -> str:
    h = hashlib.blake2b(digest_size=16)
    for part in parts:
        if isinstance(part, bytes):
            h.update(part)
        else:
            h.update(repr(part).encode())
        h.update(b"\x00")
    return h.hexdigest()


def def_components(model) -> dict:
    """The model definition factored into per-component digests:
    ``{"geometry", "init", "expand", "boundary", "repr", "props"}`` —
    abstract jaxpr tracing only, nothing executes on a device. The
    vector is recorded (JSON) in the family/spec index rows at publish,
    which is what `classify` diffs a new model against."""
    cache_key = id(model)
    cached = _COMPONENT_CACHE.get(cache_key)
    if cached is not None and cached[0]() is model:
        return cached[1]
    import jax
    import jax.numpy as jnp

    from .corpus import FORMAT

    probe = jax.ShapeDtypeStruct((4, int(model.lanes)), jnp.uint32)
    init = np.asarray(model.init_states(), dtype=np.uint32)
    comps = {
        "geometry": _digest(
            "geometry", jax.__version__, FORMAT, type(model).__name__,
            int(model.lanes), int(model.max_actions),
        ),
        "init": _digest("init", init.shape, init.tobytes()),
        "expand": _digest(
            "expand", str(jax.make_jaxpr(model.expand)(probe))
        ),
        "boundary": _digest(
            "boundary", str(jax.make_jaxpr(model.within_boundary)(probe))
        ),
        "repr": (
            _digest(
                "repr", str(jax.make_jaxpr(model.representative)(probe))
            )
            if model.representative is not None else ""
        ),
        "props": {
            p.name: _digest(
                "prop", p.name, p.expectation.value,
                str(
                    jax.make_jaxpr(
                        lambda s, _c=p.condition: _c(model, s)
                    )(probe)
                ),
            )
            for p in model.properties()
        },
    }
    try:
        ref = weakref.ref(
            model, lambda _r, k=cache_key: _COMPONENT_CACHE.pop(k, None)
        )
        _COMPONENT_CACHE[cache_key] = (ref, comps)
    except TypeError:
        pass  # weakref-less exotic model: just re-trace next time
    return comps


def joint_def_hash(comps: dict) -> str:
    """The joint definition hash DERIVED from the component digests —
    `corpus.model_def_hash` is exactly this over `def_components(model)`,
    so the factored vector and the monolithic key cannot drift. Property
    digests fold in sorted-name order (results are property-order
    invariant: each property observes states independently)."""
    h = hashlib.blake2b(digest_size=16)
    for part in _CORE_PARTS:
        h.update(str(comps[part]).encode())
        h.update(b"\x00")
    for name in sorted(comps["props"]):
        h.update(name.encode())
        h.update(b"\x01")
        h.update(str(comps["props"][name]).encode())
        h.update(b"\x00")
    return h.hexdigest()


def spec_core_hash(comps: dict, tenant: Optional[str] = None) -> str:
    """The spec-index address: the GEOMETRY digest alone (salted per
    tenant exactly like the family "def" component). Keying the index by
    geometry — not the joint hash — is what makes EVERY edit class
    findable: an `expand` edit still lands in the same spec family, so
    its refusal is classified and counted instead of silently missing."""
    core = str(comps["geometry"])
    if tenant is not None:
        core = hashlib.blake2b(
            (core + ":tenant:" + tenant).encode(), digest_size=16
        ).hexdigest()
    return core


def classify(new_comps: dict, old_comps) -> str:
    """Name the edit class between a new model's component vector and a
    recorded one. Any malformed, missing, or pre-delta `old_comps` (a
    family row written before this subsystem recorded component vectors)
    classifies ``"expand/init"`` — unsalvageable, never misclassified —
    which degrades to the existing exact/near/partial ladder."""
    if not isinstance(old_comps, dict):
        return "expand/init"
    old_props = old_comps.get("props")
    new_props = new_comps.get("props")
    if not isinstance(old_props, dict) or not isinstance(new_props, dict):
        return "expand/init"
    for part in ("geometry", "init", "expand", "repr"):
        if old_comps.get(part) != new_comps.get(part):
            return "expand/init"
    if not old_comps.get("boundary") or not new_comps.get("boundary"):
        return "expand/init"
    boundary_same = old_comps["boundary"] == new_comps["boundary"]
    props_same = old_props == new_props
    if boundary_same and props_same:
        return "identical"
    if boundary_same:
        return "properties-only"
    if props_same:
        return "boundary-only"
    return "expand/init"  # mixed edit: no sound salvage rule


def component_reuse(new_comps: dict, old_comps: dict) -> int:
    """How many component digests a salvage reuses unchanged (the
    `component_reuse` REGISTRY counter): the equal core parts plus every
    per-property digest present unchanged in both vectors."""
    n = sum(
        1
        for part in _CORE_PARTS
        if old_comps.get(part) == new_comps.get(part)
    )
    old_props = old_comps.get("props") or {}
    new_props = new_comps.get("props") or {}
    n += sum(
        1 for name, d in new_props.items() if old_props.get(name) == d
    )
    return n


def _batched_eval(fn, states: np.ndarray) -> np.ndarray:
    """Evaluate a batched bool predicate over uint32[n, L] host rows in
    `_EVAL_BATCH` chunks (eager, no jit — salvage runs once per lookup)."""
    import jax.numpy as jnp

    n = int(len(states))
    if n == 0:
        return np.zeros(0, dtype=bool)
    out = []
    for b0 in range(0, n, _EVAL_BATCH):
        out.append(
            np.asarray(fn(jnp.asarray(states[b0 : b0 + _EVAL_BATCH])))
        )
    return np.concatenate(out).astype(bool)


def eval_boundary(model, states: np.ndarray) -> np.ndarray:
    """bool[n]: `model.within_boundary` over packed journal rows — the
    publish-side hook that records `journal_bound` (the B_old plane the
    boundary-only salvage rule diffs against)."""
    return _batched_eval(model.within_boundary, states)


def _journal_planes(entry):
    """The entry's recorded journal planes, alignment-checked against the
    fingerprint rows, or None when the entry predates them (published by
    a pre-delta version, or grown from a resumed journal whose states
    were unrecoverable)."""
    j_states = getattr(entry, "journal_states", None)
    j_depths = getattr(entry, "journal_depths", None)
    if j_states is None or j_depths is None:
        return None
    if len(j_states) != len(entry.fps) or len(j_depths) != len(entry.fps):
        return None
    return np.asarray(j_states, np.uint32), np.asarray(j_depths, np.uint32)


def _finish_matches(finish_when, props, discovered: set) -> bool:
    """Would a run with this discovery set early-exit? (The scheduler's
    per-step check: all properties discovered, or finish_when satisfied.)"""
    if props and len(discovered) == len(props):
        return True
    return finish_when is not None and finish_when.matches(
        props, discovered
    )


def salvage_properties(
    entry,
    model,
    finish_when,
    target_state_count: Optional[int],
    target_max_depth: Optional[int],
    new_comps: dict,
):
    """The properties-only salvage rule (soundness argument in the module
    docstring): returns a COMPLETE entry whose meta carries the
    re-evaluated discovery set — served exactly like an exact/near
    replay, under the ``"delta"`` kind — or None (refuse, cold)."""
    old_comps = (getattr(entry, "components", None) or {}).get("comps")
    if classify(new_comps, old_comps) != "properties-only":
        return None
    if not getattr(entry, "complete", False):
        return None
    planes = _journal_planes(entry)
    if planes is None:
        return None
    j_states, j_depths = planes
    from .corpus import finish_signature

    comp = entry.components or {}
    fin = finish_signature(finish_when, target_state_count, target_max_depth)
    if comp.get("finish") != repr(tuple(fin)):
        return None  # different stop policy: pop order parity unproven
    props = list(model.properties())
    old_props = old_comps.get("props") or {}
    new_props = new_comps.get("props") or {}
    old_disc = entry.meta.get("discoveries", {})
    ev = (
        np.ones(len(j_states), dtype=bool)
        if target_max_depth is None
        else j_depths < np.uint32(target_max_depth)
    )
    merged: dict = {}
    for p in props:
        if new_props.get(p.name) == old_props.get(p.name):
            # Unchanged digest => identical condition jaxpr => identical
            # verdicts on the identical pop stream: the recorded first
            # witness (or recorded absence) replays verbatim.
            if p.name in old_disc:
                merged[p.name] = int(old_disc[p.name])
            continue
        if p.expectation is Expectation.EVENTUALLY:
            # Liveness needs the pending-bit/terminality plane the entry
            # does not record — refuse rather than approximate.
            return None
        cond = p.condition
        sat = _batched_eval(lambda s, _c=cond: _c(model, s), j_states)
        if p.expectation is Expectation.ALWAYS:
            hit = ev & ~sat
        else:  # SOMETIMES: first witness
            hit = ev & sat
        if hit.any():
            merged[p.name] = int(
                np.asarray(entry.fps, np.uint64)[int(np.argmax(hit))]
            )
    if _finish_matches(finish_when, props, set(merged)):
        # The edited properties make the finish policy satisfiable: a
        # cold run would early-exit mid-stream with smaller counts than
        # this full-exhaustion entry — refuse, never replay wrong counts.
        return None
    meta = dict(entry.meta)
    meta["discoveries"] = merged
    return dataclasses.replace(entry, meta=meta)


def salvage_boundary(
    entry,
    model,
    finish_when,
    target_state_count: Optional[int],
    target_max_depth: Optional[int],
    new_comps: dict,
):
    """The boundary-only salvage rule (soundness argument in the module
    docstring): returns a PARTIAL entry whose frontier re-expands the
    WHOLE visited set under the edited predicate (the engines mask the
    boundary at claim time, so the edit's effect is only visible on the
    successors the old run never recorded — every visited row may have
    declined one). The caller must mark the job no-publish. Refuses
    (returns None) when any visited row or any newly-admitted init
    falls outside the new predicate, when the stop point is
    traversal-order sensitive (count/depth targets, a prefix-satisfied
    finish), or when any property is EVENTUALLY."""
    old_comps = (getattr(entry, "components", None) or {}).get("comps")
    if classify(new_comps, old_comps) != "boundary-only":
        return None
    if not getattr(entry, "complete", False):
        return None
    planes = _journal_planes(entry)
    if planes is None:
        return None
    j_states, j_depths = planes
    b_new = eval_boundary(model, j_states)
    if not bool(b_new.all()):
        # A visited state is excluded under the edit (narrowing — or a
        # mixed reshape that narrows anywhere the old run looked): V
        # over-approximates Reach_new.
        return None
    # Refuse whenever the stop point is traversal-order sensitive —
    # count/depth targets, a prefix-satisfied finish, or liveness.
    if target_state_count is not None or target_max_depth is not None:
        return None
    props = list(model.properties())
    if any(p.expectation is Expectation.EVENTUALLY for p in props):
        return None
    prefix_disc = set(entry.meta.get("discoveries", {}))
    if _finish_matches(finish_when, props, prefix_disc):
        return None  # already satisfied inside the prefix: cold stops sooner
    # Root coverage: every init the new predicate admits must already be
    # in V, else it roots a subtree unreachable from V's rows.
    import jax.numpy as jnp

    from ..tensor.fingerprint import pack_fp
    from ..tensor.frontier import state_fingerprint
    from .warm import split_fps

    init = np.asarray(model.init_states(), dtype=np.uint32)
    in_b = eval_boundary(model, init)
    n_raw = int(in_b.sum())
    init = init[in_b]
    fps = np.asarray(entry.fps, np.uint64)
    if len(init):
        i_lo, i_hi = (
            np.asarray(x)
            for x in state_fingerprint(model, jnp.asarray(init))
        )
        if not set(pack_fp(i_lo, i_hi).tolist()) <= set(fps.tolist()):
            return None
    lo, hi = split_fps(fps)
    frontier = {
        "states": j_states,
        "lo": lo,
        "hi": hi,
        "ebits": np.zeros((len(j_states), len(props)), dtype=bool),
        "depths": j_depths,
    }
    meta = dict(entry.meta)
    # The continuation re-pops every prefix row, re-counting its
    # generated successors: the baseline must be the raw admitted-init
    # count (scheduler.admit's own seed), not the prefix's full tally.
    meta["state_count"] = n_raw
    return dataclasses.replace(
        entry, meta=meta, complete=False, frontier=frontier
    )


def salvage(
    entry,
    model,
    delta_class: str,
    finish_when,
    target_state_count: Optional[int],
    target_max_depth: Optional[int],
    new_comps: dict,
):
    """Dispatch the classified edit to its salvage rule. Returns the
    servable entry (complete => replay, partial => continuation the
    caller must mark no-publish) or None — every unknown class refuses."""
    if delta_class == "properties-only":
        return salvage_properties(
            entry, model, finish_when, target_state_count,
            target_max_depth, new_comps,
        )
    if delta_class == "boundary-only":
        return salvage_boundary(
            entry, model, finish_when, target_state_count,
            target_max_depth, new_comps,
        )
    return None
