"""Host spill tier: the cold half of the tiered state store.

Holds every fingerprint evicted from the device hash table as packed uint64
arrays (fingerprint + parent fingerprint, aligned), the host analogue of
disk-based Murphi's state file. Two-zone layout for O(log n) membership with
O(1) appends:

- a SORTED zone (deduped, binary-searchable), and
- PENDING append chunks in arrival order, merged into the sorted zone by a
  background compaction thread once they pile past a threshold (or inline
  when `background=False` — deterministic for tests).

Dedup keeps the FIRST-appended entry per fingerprint: eviction can re-spill
a key that was re-claimed on device after an earlier spill, and the first
entry carries the ORIGINAL parent — the one the BFS discovery wrote — which
is what keeps reconstructed paths acyclic (a later re-claim's parent can sit
deeper than the state itself).

All public methods are thread-safe (one lock shared with the compactor);
`contains` is the hot host-side operation — it runs once per SUSPECT batch,
not per state, so a searchsorted over the sorted zone plus an isin over the
small pending tail is plenty.
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional

import numpy as np

from ..faults.plan import maybe_fault


def _compactor_loop(store_ref, wake: threading.Event) -> None:
    """Background compactor body. Holds only a WEAKREF to the store: a
    dropped store's fingerprint arrays stay collectable (the spill tier is
    by design the thing that can outgrow HBM — a parked thread must not
    pin it), and the thread reaps itself once the store is gone or
    closed. Module-level so the thread closure captures no `self`."""
    while True:
        wake.wait(timeout=30.0)
        wake.clear()
        store = store_ref()
        if store is None or store._stop:
            return
        store.compact()
        del store


class HostSpillStore:
    def __init__(
        self,
        compact_threshold: int = 1 << 15,
        background: bool = True,
    ):
        self._lock = threading.RLock()
        self._sorted_fps = np.zeros(0, dtype=np.uint64)
        self._sorted_parents = np.zeros(0, dtype=np.uint64)
        self._pending: list[tuple[np.ndarray, np.ndarray]] = []
        self._pending_len = 0
        self._compact_threshold = compact_threshold
        self._wake: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        if background:
            self._wake = threading.Event()
            self._thread = threading.Thread(
                target=_compactor_loop,
                args=(weakref.ref(self), self._wake),
                daemon=True,
            )
            self._thread.start()

    def close(self) -> None:
        """Stop the background compactor. MUST be called when a store is
        replaced (engine reset / checkpoint restore): the parked thread
        holds a reference to this store, so without it every reset would
        leak a thread plus a full copy of the spilled fingerprint set —
        the one array designed to outgrow HBM."""
        if self._thread is not None:
            self._stop = True
            self._wake.set()
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- writes ---------------------------------------------------------------

    def append(self, fps: np.ndarray, parents: np.ndarray) -> None:
        """Append one eviction batch (packed uint64, aligned)."""
        fps = np.asarray(fps, dtype=np.uint64)
        parents = np.asarray(parents, dtype=np.uint64)
        if fps.size == 0:
            return
        # Chaos-plane boundary: the append is the spill tier's write path —
        # an I/O fault here fires BEFORE the batch lands, so the store
        # never holds half an eviction batch (faults/plan.py).
        maybe_fault("store.append", n=int(fps.size))
        with self._lock:
            self._pending.append((fps.copy(), parents.copy()))
            self._pending_len += fps.size
            if self._pending_len >= self._compact_threshold:
                if self._wake is not None:
                    self._wake.set()
                else:
                    self._compact_locked()

    def compact(self) -> None:
        """Merge pending chunks into the sorted zone (first-writer dedup)."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        if not self._pending:
            return
        # Concatenation order = append order (sorted zone predates every
        # pending chunk), so np.unique's first-occurrence index implements
        # exactly the first-writer-wins parent rule.
        all_fps = np.concatenate(
            [self._sorted_fps] + [f for f, _ in self._pending]
        )
        all_parents = np.concatenate(
            [self._sorted_parents] + [p for _, p in self._pending]
        )
        uniq, first = np.unique(all_fps, return_index=True)
        self._sorted_fps = uniq
        self._sorted_parents = all_parents[first]
        self._pending = []
        self._pending_len = 0


    # -- reads ----------------------------------------------------------------

    def contains(self, fps: np.ndarray) -> np.ndarray:
        """bool[n]: exact membership for packed fingerprints."""
        fps = np.asarray(fps, dtype=np.uint64)
        with self._lock:
            pos = np.searchsorted(self._sorted_fps, fps)
            pos = np.minimum(pos, max(self._sorted_fps.size - 1, 0))
            hit = (
                self._sorted_fps[pos] == fps
                if self._sorted_fps.size
                else np.zeros(fps.shape, dtype=bool)
            )
            for chunk, _ in self._pending:
                hit |= np.isin(fps, chunk)
            return hit

    def __len__(self) -> int:
        """Deduped spilled-state count (compacts to make it exact)."""
        with self._lock:
            self._compact_locked()
            return int(self._sorted_fps.size)

    def parent_map(self) -> dict:
        """{fingerprint: parent fingerprint} for path reconstruction."""
        with self._lock:
            self._compact_locked()
            return dict(
                zip(
                    self._sorted_fps.tolist(),
                    self._sorted_parents.tolist(),
                )
            )

    # -- checkpoint -----------------------------------------------------------

    def to_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(fps, parents) snapshot for checkpointing (compacted)."""
        with self._lock:
            self._compact_locked()
            return self._sorted_fps.copy(), self._sorted_parents.copy()

    @classmethod
    def from_arrays(
        cls, fps: np.ndarray, parents: np.ndarray, background: bool = True
    ) -> "HostSpillStore":
        s = cls(background=background)
        s._sorted_fps = np.asarray(fps, dtype=np.uint64)
        s._sorted_parents = np.asarray(parents, dtype=np.uint64)
        return s
