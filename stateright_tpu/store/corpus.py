"""Cross-job warm-start corpus: a persistent, content-addressed store of
completed jobs' visited-set fingerprints (ROADMAP item 4).

Millions of users re-checking near-identical models re-explore the same
state spaces from scratch. This module closes that loop at the service
level: when a job runs its model to exhaustion, the service publishes the
job's full visited set — packed (fingerprint, parent-fingerprint) uint64
arrays in exactly the host spill tier's on-disk shape (store/host.py), plus
a serialized Bloom summary of the set — as one crash-atomic, CRC-checked
`faults/ckptio.py` generation addressed by a CONTENT key. A later
submission with the same key preloads the corpus into the tiered store's
spill tier + device Bloom summary before seeding, so every known state is
dedup-filtered on device at its first re-appearance (Bloom-positive probes
resolve exactly on host, reusing the r7 suspect path) and the search
collapses to re-expanding only the init frontier, while result bookkeeping
replays the publisher's counts/discoveries/parent chains — bit-identical
to a cold run, ≥5x faster.

The content key is a blake2b digest of the MODEL DEFINITION (init states,
the abstract jaxprs of expand / within_boundary / every property condition
/ the symmetry representative — i.e. the lowered transition system itself,
not the Python object identity) combined with the lowering + table-layout
config and the finish policy. Two submissions share a corpus entry iff a
cold run of both would provably produce the same visited set and the same
result.

Addressing is content-addressed ckptio (`faults/ckptio.content_path`):
entries are plain atomic_savez generations named by the key, so fleet
replicas pointed at one shared corpus directory SHARE generations — the
first replica to finish a key publishes it, every other replica's publish
of the same key is skipped (`publish_skipped`), and all of them warm-start
from the one file. Robustness is never traded for speed: a corpus entry
with a bad CRC or a truncated tail is detected by the ckptio footer check,
counted (`corrupt_entries`, exported through the obs REGISTRY "corpus"
source), and IGNORED — the job simply runs cold, it never returns wrong
results. Both sides of the corpus are chaos-plane boundaries
(``corpus.load`` / ``corpus.publish`` in faults/plan.py): an injected
fault at either degrades to a cold run / an unpublished entry, proven by
tests/test_corpus.py.

Corpus v2 adds delta-proportional re-verification on top of the exact-key
store above (the "CI for protocol specs" end state of ROADMAP item 4):

- PARTIAL entries (`complete=False`): a run cut short — early exit,
  preemption, timeout, budget cap — publishes what it visited plus a
  frontier snapshot at ``corpus-partial-<key>.npz``; a successor resumes
  the snapshot as a FIFO prefix instead of starting cold, and the first
  COMPLETE publish under the key deletes the partial it supersedes
  (`superseded_entries`). Partials are latest-wins (not if_absent): a
  longer prefix replaces a shorter one.
- The FAMILY index (`corpus-family-<def_hash>.npz`): one tiny advisory
  record per model-definition hash listing every published key with its
  factored components (`key_components`: batch_size, finish signature,
  table packing). An exact-key miss falls back to a family match — same
  definition, different `table_log2`/`insert_variant`/finish — because
  set MEMBERSHIP is packing-invariant; only the salt-rekeying
  `TieredStore.preload` packing differs (`near_match_hits`). The index is
  best-effort and latest-wins: a stale or missing record only costs a
  cold run, never a wrong one.
- The soundness rules for which entry may warm which run (replay vs
  continue vs membership-only) live in ONE place: store/warm.py.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..faults.blobstore import blob_backend, is_blob_uri, normalize_root
from ..faults.ckptio import (
    CheckpointCorrupt,
    LeaseRevoked,
    any_generation,
    content_path,
    fenced_load_latest,
    fenced_savez,
    latest_generation,
)
from ..faults.plan import FaultError, maybe_fault
from ..obs import REGISTRY
from .specdelta import def_components, joint_def_hash, spec_core_hash
from .summary import host_insert, summary_words

#: Corpus payload format version (bumped on incompatible array layouts; a
#: mismatched entry is treated exactly like a corrupt one: ignored, cold).
FORMAT = 1


def model_def_hash(model) -> str:
    """blake2b digest of a TensorModel's DEFINITION: class name, lane
    geometry, concrete init states, and the abstract jaxprs of `expand`,
    `within_boundary`, every property condition, and the symmetry
    representative (when present). Abstract tracing only — nothing
    executes on a device — and jaxpr printing is deterministic for a
    given jax version (which is folded into the digest), so equal-config
    model instances hash equal across processes and fleet replicas while
    any change to the transition system, the properties, or the state
    encoding changes the key.

    Spec-CI (store/specdelta.py): the digest is DERIVED from the
    per-component digests of `specdelta.def_components` — the factored
    vector the delta classifier diffs — so the joint key and the
    factoring can never disagree (the per-model trace cache lives
    there)."""
    return joint_def_hash(def_components(model))


def content_key(model, lowering: dict, tenant: Optional[str] = None) -> str:
    """The corpus content address for (model definition, lowering config).

    `lowering` must hold every knob that can change the visited set, the
    claim/pop order, or the finish point of a run: batch_size, table_log2,
    insert_variant, summary config, and the finish policy (finish_when
    kind+names, target_state_count, target_max_depth). Values must be
    repr-stable scalars/tuples.

    `tenant` (service/tenancy.py) salts the key into a per-tenant
    namespace so one tenant's published entries never warm another's
    runs; ``None`` (the default tenant) leaves the bytes identical to the
    pre-tenancy key, so existing corpora keep serving."""
    h = hashlib.blake2b(digest_size=16)
    h.update(model_def_hash(model).encode())
    if tenant is not None:
        h.update(b"tenant:" + tenant.encode())
    h.update(repr(sorted(lowering.items())).encode())
    return h.hexdigest()


def key_components(
    model, lowering: dict, tenant: Optional[str] = None
) -> dict:
    """The content key factored into its near-match components (corpus v2):
    the definition hash (the family address), the result-affecting run
    shape (batch_size + finish policy — pop order and the stop point), and
    the result-INVARIANT table packing (everything else in the lowering:
    table_log2, insert_variant, summary geometry, store kind). Two runs
    whose "def"/"batch_size"/"finish" components agree produce identical
    results from identical prefixes regardless of "table" — that is the
    near-match rung of the warm ladder (store/warm.py).

    The tenant salt lands in the **"def"** component, not "table":
    `lookup_near`/`lookup_family` match on def+batch_size+finish and
    ignore "table", so salting anywhere weaker would let a near-match
    rung serve one tenant's states to another. ``None`` keeps the
    pre-tenancy component bytes.

    Spec-CI (store/specdelta.py) adds two entries: "core" — the
    geometry-only spec-index address (tenant-salted exactly like "def"),
    under which EVERY edit of the same model geometry is findable — and
    "comps" — the raw per-component digest vector the delta classifier
    diffs (recorded verbatim in the family/spec index rows and the
    entry payload at publish)."""
    fin = lowering.get("finish")
    comps = def_components(model)
    def_hash = joint_def_hash(comps)
    if tenant is not None:
        def_hash = hashlib.blake2b(
            (def_hash + ":tenant:" + tenant).encode(), digest_size=16
        ).hexdigest()
    return {
        "def": def_hash,
        "core": spec_core_hash(comps, tenant=tenant),
        "comps": comps,
        "batch_size": int(lowering.get("batch_size", 0)),
        "finish": repr(tuple(fin)) if fin is not None else repr(None),
        "table": repr(
            sorted(
                (k, v)
                for k, v in lowering.items()
                if k not in ("batch_size", "finish")
            )
        ),
    }


def finish_signature(finish_when, target_state_count, target_max_depth):
    """The finish-policy component of a content key (HasDiscoveries is a
    frozen dataclass; its kind + sorted names identify it exactly)."""
    return (
        finish_when.kind,
        tuple(sorted(finish_when.names)),
        target_state_count,
        target_max_depth,
    )


_EMPTY_U64 = np.zeros(0, dtype=np.uint64)
_EMPTY_U8 = np.zeros(0, dtype=np.uint8)


@dataclass
class CorpusEntry:
    """One published visited set: packed host-tier arrays + the serialized
    Bloom summary + the result metadata a warm run replays + the semantics
    plane's packed (canonical history fingerprint -> verdict bit) table
    (dedup-first semantics, ROADMAP item 5: verdicts are content-addressed
    by canonical equivalence class, so any job's table warm-starts every
    other's consistency-property evaluation)."""

    key: str
    fps: np.ndarray  # uint64[n] packed unsalted fingerprints
    parents: np.ndarray  # uint64[n] packed unsalted parent fps (0 = root)
    summary: np.ndarray  # uint32 Bloom words over the unsalted set
    summary_log2: int
    summary_hashes: int
    meta: dict  # state_count / unique_count / max_depth / discoveries
    sem_fps: np.ndarray = None  # uint64[m] canonical history fingerprints
    sem_verdicts: np.ndarray = None  # uint8[m] serialization verdict bits
    #: Corpus v2: False for a partial entry (run cut short — the meta
    #: counts cover only the published prefix). v1 payloads decode True.
    complete: bool = True
    #: Partial entries only: the FIFO frontier snapshot at the cut —
    #: {"states" u32[n,L], "lo" u32[n], "hi" u32[n], "ebits" bool[n,P],
    #: "depths" u32[n]}, unsalted, in pop order. None for complete
    #: entries and for coverage-only partials (simulation), which warm
    #: membership but cannot be continued.
    frontier: Optional[dict] = None
    #: The factored content-key components (`key_components`) recorded at
    #: publish — what the near-match ladder (store/warm.py) reasons over.
    components: Optional[dict] = None
    #: Spec-CI journal planes (store/specdelta.py), COMPLETE entries only
    #: and aligned row-for-row with `fps`: the claimed state rows in pop
    #: order (uint32[n, L]), their pop depths (uint32[n]), and the
    #: publisher boundary's verdict over them (bool[n]). None on entries
    #: published before the delta subsystem (or grown from a resumed
    #: journal) — the delta rung then refuses, degrading to exact/near.
    journal_states: Optional[np.ndarray] = None
    journal_depths: Optional[np.ndarray] = None
    journal_bound: Optional[np.ndarray] = None

    def __post_init__(self):
        if self.sem_fps is None:
            self.sem_fps = _EMPTY_U64
        if self.sem_verdicts is None:
            self.sem_verdicts = _EMPTY_U8

    @property
    def states(self) -> int:
        return int(self.fps.size)

    @property
    def verdicts(self) -> int:
        return int(self.sem_fps.size)


class CorpusStore:
    """The content-addressed corpus directory. Thread-safe; one instance
    per service engine (fleet replicas each build one over the SHARED
    directory — the content addressing is what de-duplicates their
    writes). Counters are exported through the obs REGISTRY ("corpus"
    source) so hit/miss/corrupt rates are scrapeable at `/metrics`."""

    def __init__(
        self,
        root: str,
        summary_log2: int = 20,
        summary_hashes: int = 4,
    ):
        summary_words(summary_log2)  # validates >= 5
        self.root = normalize_root(root)
        self.summary_log2 = summary_log2
        self.summary_hashes = summary_hashes
        if not is_blob_uri(self.root):
            os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        # Epoch fence (service/lease.py, fleet replicas only): set by the
        # owning Replica driver via `set_lease`. A fenced corpus refuses
        # its own publishes once the lease is revoked, stamps every entry
        # it writes, and rejects stale-stamped entries at lookup — the
        # "zombie double-publish" hazard closed at both ends.
        self._lease = None
        # Entries a live job preloaded: `gc` refuses to evict them.
        # {content key: pin count} managed by the service scheduler
        # (pin at warm admission, unpin at job finalize).
        self._pinned: dict = {}
        self.counters = {
            "hits": 0,
            "misses": 0,
            "publishes": 0,
            "publish_skipped": 0,
            "publish_faults": 0,
            "load_faults": 0,
            "corrupt_entries": 0,
            "lease_rejected": 0,
            "preload_states": 0,
            "verdict_preloads": 0,
            "verdicts_published": 0,
            "partial_publishes": 0,
            "partial_preloads": 0,
            "near_match_hits": 0,
            "superseded_entries": 0,
            "delta_hits": 0,
            "delta_refusals": 0,
            "component_reuse": 0,
            "gc_sweeps": 0,
            "gc_evicted": 0,
            "gc_bytes_freed": 0,
            "gc_pinned_skips": 0,
            "gc_faults": 0,
        }
        self._metrics_name = REGISTRY.register("corpus", self.metrics)

    def set_lease(self, lease) -> None:
        """Attach the owning replica's fencing token (service/lease.py
        Lease); publishes re-validate it and entries carry its stamp."""
        self._lease = lease

    def path_for(self, key: str) -> str:
        return content_path(self.root, key)

    def partial_path_for(self, key: str) -> str:
        """The partial entry's generation path — a sibling name under the
        same ``corpus-`` gc listing prefix, never colliding with the
        complete entry (content keys are hex; "partial-<key>" is not)."""
        return content_path(self.root, key, kind="corpus-partial")

    def _family_path(self, def_hash: str) -> str:
        return content_path(self.root, def_hash, kind="corpus-family")

    def _spec_path(self, core_hash: str) -> str:
        """The spec index record for one model GEOMETRY (specdelta
        `spec_core_hash`) — the cross-DEFINITION sibling of the family
        index, listing every published key with its component-digest
        vector so a definition edit can still find (and classify
        against) its predecessors."""
        return content_path(self.root, core_hash, kind="corpus-spec")

    def _count(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self.counters[counter] += n

    # -- read side -------------------------------------------------------------

    def lookup(self, key: str) -> Optional[CorpusEntry]:
        """The newest intact COMPLETE generation for `key`, or None. NEVER
        raises: a missing entry is a miss, a corrupt one (CRC/container
        failure on every generation) is counted and ignored, and an
        injected ``corpus.load`` fault degrades to a miss — warm-start is
        an optimization, so every failure mode here means "run cold"."""
        return self._lookup_at(key, self.path_for(key))

    def lookup_partial(self, key: str) -> Optional[CorpusEntry]:
        """The newest intact PARTIAL generation for `key`, or None — same
        never-raises contract (and the same ``corpus.load`` chaos point)
        as `lookup`. Callers must gate continuation on
        `store/warm.can_continue`; a decoded complete-flagged payload at
        the partial path (cannot happen via `publish`, but the ladder is
        defensive) is returned as-is and declined there."""
        return self._lookup_at(key, self.partial_path_for(key))

    def _lookup_at(self, key: str, path: str) -> Optional[CorpusEntry]:
        fenced_out = []
        try:
            # Chaos-plane boundary: fires before any file is touched, so a
            # faulted load leaves the corpus (and the job) untouched.
            maybe_fault("corpus.load", key=key[:16])
            if not any_generation(path):
                self._count("misses")
                return None
            def reject(*stamp):
                fenced_out.append(stamp)
                self._count("lease_rejected")

            data, _src = fenced_load_latest(
                path,
                validator=(
                    self._lease.store.validate
                    if self._lease is not None else None
                ),
                on_reject=reject,
            )
            entry = self._decode(key, data)
        except (FaultError, OSError) as e:
            self._count("load_faults")
            self._count("misses")
            del e
            return None
        except CheckpointCorrupt:
            # Torn tail / flipped byte / truncated entry — or every
            # candidate stamped with a REVOKED lease epoch (a zombie's
            # publish that raced the revocation: stale, never read back).
            # Either way: ignore the entry — cold, never wrong.
            if not fenced_out:
                self._count("corrupt_entries")
            self._count("misses")
            return None
        finally:
            if fenced_out and self._lease is not None:
                self._lease.store.count_rejected("read", len(fenced_out))
        if entry is None:
            self._count("corrupt_entries")
            self._count("misses")
            return None
        self._count("hits")
        return entry

    def _decode(self, key: str, data) -> Optional[CorpusEntry]:
        """npz -> CorpusEntry; None when the payload is not a corpus entry
        for this key (schema drift, hash collision defense)."""
        try:
            stored_key = str(np.asarray(data["key"]).reshape(-1)[0])
            fmt = int(np.asarray(data["format"]).reshape(-1)[0])
            if stored_key != key or fmt != FORMAT:
                return None
            cfg = np.asarray(data["cfg"], dtype=np.int64)
            counts = np.asarray(data["counts"], dtype=np.int64)
            discoveries = {
                str(n): int(f)
                for n, f in zip(data["d_names"], data["d_fps"])
            }
            # Semantics verdict table: optional (entries published before
            # the dedup-first plane, or by verdict-less jobs, simply lack
            # the keys — warm-start degrades to visited-set-only).
            names = getattr(data, "files", data)
            has_sem = "sem_fps" in names and "sem_verdicts" in names
            complete = True
            if "complete" in names:
                complete = bool(int(np.asarray(data["complete"]).reshape(-1)[0]))
            frontier = None
            if "f_lo" in names:
                frontier = {
                    "states": np.asarray(data["f_states"], dtype=np.uint32),
                    "lo": np.asarray(data["f_lo"], dtype=np.uint32),
                    "hi": np.asarray(data["f_hi"], dtype=np.uint32),
                    "ebits": np.asarray(data["f_ebits"], dtype=bool),
                    "depths": np.asarray(data["f_depths"], dtype=np.uint32),
                }
            components = None
            if "comp" in names:
                components = json.loads(
                    str(np.asarray(data["comp"]).reshape(-1)[0])
                )
            j_states = j_depths = j_bound = None
            if "j_states" in names:
                j_states = np.asarray(data["j_states"], dtype=np.uint32)
                j_depths = np.asarray(data["j_depths"], dtype=np.uint32)
            if "j_bound" in names:
                j_bound = np.asarray(data["j_bound"], dtype=bool)
            return CorpusEntry(
                key=key,
                fps=np.asarray(data["fps"], dtype=np.uint64),
                parents=np.asarray(data["parents"], dtype=np.uint64),
                summary=np.asarray(data["summary"], dtype=np.uint32),
                summary_log2=int(cfg[0]),
                summary_hashes=int(cfg[1]),
                meta={
                    "state_count": int(counts[0]),
                    "unique_count": int(counts[1]),
                    "max_depth": int(counts[2]),
                    "discoveries": discoveries,
                },
                sem_fps=(
                    np.asarray(data["sem_fps"], dtype=np.uint64)
                    if has_sem else None
                ),
                sem_verdicts=(
                    np.asarray(data["sem_verdicts"], dtype=np.uint8)
                    if has_sem else None
                ),
                complete=complete,
                frontier=frontier,
                components=components,
                journal_states=j_states,
                journal_depths=j_depths,
                journal_bound=j_bound,
            )
        except (KeyError, ValueError, IndexError):
            return None

    def note_preload(self, n: int) -> None:
        """Account states actually preloaded into a tiered store."""
        self._count("preload_states", n)

    def note_partial_preload(self) -> None:
        """Account one warm-from-partial admission (the `partial_preloads`
        REGISTRY counter; per-state accounting stays in `note_preload`)."""
        self._count("partial_preloads")

    def note_delta_hit(self, reused_components: int = 0) -> None:
        """Account one delta-rung salvage (Spec-CI): a definition edit
        served a warm start through store/specdelta.py. `reused_components`
        is how many component digests carried over unchanged."""
        self._count("delta_hits")
        if reused_components:
            self._count("component_reuse", reused_components)

    def note_delta_refusal(self, n: int = 1) -> None:
        """Account delta-rung candidates REFUSED by the salvage rules —
        the counted, provably-cold path (`delta_refusals`): an expand/init
        edit, a pre-delta record without a component vector, a narrowed
        boundary, or an order-sensitive finish."""
        if n:
            self._count("delta_refusals", n)

    # -- near-match family index (corpus v2) -----------------------------------

    def family_members(self, def_hash: str) -> list:
        """The advisory member list for a definition-hash family: dicts of
        {key, complete, states, batch_size, finish, table}. Best-effort —
        a missing, corrupt, faulted, or lease-rejected record reads as an
        empty family (a near-match miss, never an error)."""
        try:
            maybe_fault("corpus.load", key=def_hash[:16])
            path = self._family_path(def_hash)
            if not any_generation(path):
                return []
            data, _src = fenced_load_latest(
                path,
                validator=(
                    self._lease.store.validate
                    if self._lease is not None else None
                ),
            )
            members = json.loads(str(np.asarray(data["members"]).reshape(-1)[0]))
            return members if isinstance(members, list) else []
        except (FaultError, OSError, CheckpointCorrupt, KeyError, ValueError):
            return []

    def _family_note(
        self, components: dict, key: str, complete: bool, states: int
    ) -> None:
        """Record (or refresh) one family member after a publish. Read-
        modify-write, latest-wins: the in-process lock serializes THIS
        replica's writers; a cross-replica race can only drop the loser's
        advisory row (a future near-match miss), never corrupt the record
        (every write is a whole crash-atomic generation). Best-effort:
        any failure leaves the index stale and the publish valid."""
        if not components or "def" not in components:
            return
        member = {
            "key": key,
            "complete": bool(complete),
            "states": int(states),
            "batch_size": int(components.get("batch_size", -1)),
            "finish": components.get("finish"),
            "table": components.get("table"),
            # Spec-CI: the per-component digest vector rides in the family
            # row too, alongside the joint hash the family is keyed by —
            # so the factored key is recorded wherever the entry is listed.
            "comps": components.get("comps"),
        }
        try:
            with self._lock:
                members = [
                    m for m in self.family_members(components["def"])
                    if m.get("key") != key or m.get("complete") != member["complete"]
                ]
                members.append(member)
                fenced_savez(
                    self._family_path(components["def"]),
                    {
                        "members": np.asarray(
                            [json.dumps(members)], dtype=np.str_
                        )
                    },
                    lease=self._lease,
                )
        except (FaultError, OSError, LeaseRevoked, RuntimeError):
            pass  # advisory only: a stale index is a near-match miss

    def _family_drop(self, def_hash: str, key: str, complete: bool) -> None:
        """Drop one member row (the superseded partial) — same best-effort
        read-modify-write contract as `_family_note`."""
        try:
            with self._lock:
                members = [
                    m for m in self.family_members(def_hash)
                    if m.get("key") != key or m.get("complete") != bool(complete)
                ]
                fenced_savez(
                    self._family_path(def_hash),
                    {
                        "members": np.asarray(
                            [json.dumps(members)], dtype=np.str_
                        )
                    },
                    lease=self._lease,
                )
        except (FaultError, OSError, LeaseRevoked, RuntimeError):
            pass

    # -- cross-definition spec index (Spec-CI, store/specdelta.py) -------------

    def spec_members(self, core_hash: str) -> list:
        """The advisory member list for one model GEOMETRY (`specdelta.
        spec_core_hash`): dicts of {key, def, complete, states,
        batch_size, finish, comps} spanning EVERY published definition of
        that geometry — the delta rung's candidate pool. Same best-effort
        contract as `family_members`: any failure reads as empty (a delta
        miss, never an error)."""
        try:
            maybe_fault("corpus.load", key=core_hash[:16])
            path = self._spec_path(core_hash)
            if not any_generation(path):
                return []
            data, _src = fenced_load_latest(
                path,
                validator=(
                    self._lease.store.validate
                    if self._lease is not None else None
                ),
            )
            members = json.loads(str(np.asarray(data["members"]).reshape(-1)[0]))
            return members if isinstance(members, list) else []
        except (FaultError, OSError, CheckpointCorrupt, KeyError, ValueError):
            return []

    def _spec_note(
        self, components: dict, key: str, complete: bool, states: int
    ) -> None:
        """Record one spec-index member after a publish — the family
        note's cross-definition twin (same latest-wins read-modify-write,
        same best-effort contract: a stale record costs a cold run)."""
        if (
            not components
            or not components.get("core")
            or not isinstance(components.get("comps"), dict)
        ):
            return  # pre-delta caller: no factored vector to index
        member = {
            "key": key,
            "def": components.get("def"),
            "complete": bool(complete),
            "states": int(states),
            "batch_size": int(components.get("batch_size", -1)),
            "finish": components.get("finish"),
            "comps": components.get("comps"),
        }
        try:
            with self._lock:
                members = [
                    m for m in self.spec_members(components["core"])
                    if m.get("key") != key
                    or m.get("complete") != member["complete"]
                ]
                members.append(member)
                fenced_savez(
                    self._spec_path(components["core"]),
                    {
                        "members": np.asarray(
                            [json.dumps(members)], dtype=np.str_
                        )
                    },
                    lease=self._lease,
                )
        except (FaultError, OSError, LeaseRevoked, RuntimeError):
            pass

    def _spec_drop(self, core_hash: str, key: str, complete: bool) -> None:
        """Drop one spec-index row (the superseded partial) — best-effort,
        mirroring `_family_drop`."""
        try:
            with self._lock:
                members = [
                    m for m in self.spec_members(core_hash)
                    if m.get("key") != key
                    or m.get("complete") != bool(complete)
                ]
                fenced_savez(
                    self._spec_path(core_hash),
                    {
                        "members": np.asarray(
                            [json.dumps(members)], dtype=np.str_
                        )
                    },
                    lease=self._lease,
                )
        except (FaultError, OSError, LeaseRevoked, RuntimeError):
            pass

    def lookup_near(
        self,
        components: dict,
        exclude: tuple = (),
        allow_partial: bool = True,
    ) -> Optional[CorpusEntry]:
        """Family fallback for an exact-key miss: the best published entry
        sharing `components["def"]` — ranked replayable-complete first
        (same batch_size AND finish: `warm.can_replay` will accept it),
        then continuable partials (same batch_size, any finish, most
        states first: `warm.can_continue` decides). Keys in `exclude`
        (the caller's own exact key, already tried) are skipped. A hit is
        counted as `near_match_hits`; soundness gating stays with the
        caller through store/warm.py."""
        if not components or "def" not in components:
            return None
        bs = int(components.get("batch_size", -1))
        fin = components.get("finish")
        replayable, continuable = [], []
        for m in self.family_members(components["def"]):
            if m.get("key") in exclude or m.get("batch_size") != bs:
                continue
            if m.get("complete"):
                if m.get("finish") == fin:
                    replayable.append(m)
            elif allow_partial:
                continuable.append(m)
        replayable.sort(key=lambda m: -int(m.get("states", 0)))
        continuable.sort(key=lambda m: -int(m.get("states", 0)))
        for m in replayable + continuable:
            entry = (
                self.lookup(m["key"]) if m.get("complete")
                else self.lookup_partial(m["key"])
            )
            if entry is not None:
                self._count("near_match_hits")
                return entry
        return None

    def lookup_family(self, def_hash: str) -> Optional[CorpusEntry]:
        """Membership-only family lookup: ANY intact entry for the
        definition hash, preferring complete entries with the most states.
        This is the simulation engine's rung — a shared visited table
        cares only about set membership, which every component except the
        definition is invariant to."""
        members = self.family_members(def_hash)
        members.sort(
            key=lambda m: (
                0 if m.get("complete") else 1,
                -int(m.get("states", 0)),
            )
        )
        for m in members:
            entry = (
                self.lookup(m["key"]) if m.get("complete")
                else self.lookup_partial(m["key"])
            )
            if entry is not None:
                self._count("near_match_hits")
                return entry
        return None

    def preload_verdicts(self, entry: CorpusEntry) -> int:
        """Seed the semantics plane's canonical verdict cache from the
        entry's packed table (semantics/batch.py). Returns NEW verdicts
        inserted; counted as `verdict_preloads`. Verdict bits are
        content-addressed by canonical history class, so a preload can
        never be wrong for any job — only unused."""
        if entry.sem_fps.size == 0:
            return 0
        from ..semantics.batch import preload_verdicts

        n = preload_verdicts(entry.sem_fps, entry.sem_verdicts)
        if n:
            self._count("verdict_preloads", n)
        return n

    # -- GC pinning (the service pins what live jobs preloaded) ----------------

    def pin(self, key: str) -> None:
        """Protect `key` from `gc` eviction while a live job depends on it."""
        with self._lock:
            self._pinned[key] = self._pinned.get(key, 0) + 1

    def unpin(self, key: str) -> None:
        with self._lock:
            n = self._pinned.get(key, 0) - 1
            if n <= 0:
                self._pinned.pop(key, None)
            else:
                self._pinned[key] = n

    def gc(self, max_bytes: int) -> dict:
        """mtime-LRU sweep over entry generations (ROADMAP item 4 residue):
        evict least-recently-written entries (newest generation's mtime)
        until the directory fits `max_bytes`, REFUSING to evict any entry a
        live job preloaded (`pin`). Chaos-pointed (``corpus.gc`` fires
        before any file is removed — a fault leaves the directory intact)
        and never raises: a GC failure means a bigger directory, not a
        wrong result. Returns {evicted, bytes_freed, pinned_skips,
        bytes_total}.

        The sweep runs on `BlobStore.list` METADATA through the backend
        seam (faults/blobstore.py), so eviction order is identical on
        ``file://`` and ``blob://`` roots — the local backend's listing is
        the same names/sizes/mtimes the old glob+stat walk produced, and
        the blob backend's is the server's. On a blob root the listing is
        additionally the ``blob.list`` chaos surface: a stale listing
        sweeps yesterday's view (bigger directory, never a wrong evict of
        something it can't see)."""
        out = {"evicted": 0, "bytes_freed": 0, "pinned_skips": 0,
               "bytes_total": 0}
        try:
            maybe_fault("corpus.gc", max_bytes=int(max_bytes))
        except FaultError:
            self._count("gc_faults")
            return out
        self._count("gc_sweeps")
        backend = blob_backend(self.root)
        # Group generations (entry + .prev) by content key. ONLY the two
        # committed generation names — a looser filter would also match
        # another process's in-flight `.npz.tmp.<pid>` staging file (fleet
        # replicas share the directory), and deleting that makes the
        # concurrent publish's atomic rename fail.
        entries: dict = {}
        try:
            stats = backend.list("corpus-")
        except OSError:
            self._count("gc_faults")
            return out  # unreachable store: sweep later, never wrong
        for st in stats:
            if not (
                st.name.endswith(".npz") or st.name.endswith(".npz.prev")
            ):
                continue
            key = st.name[len("corpus-"):].split(".npz")[0]
            if key.startswith("family-") or key.startswith("spec-"):
                # Family/spec index records are tiny advisory metadata
                # shared by every key in the family (resp. geometry) —
                # never evicted, never counted toward the budget.
                continue
            ent = entries.setdefault(
                key, {"names": [], "bytes": 0, "mtime": 0.0,
                      "partial": key.startswith("partial-")}
            )
            ent["names"].append(st.name)
            ent["bytes"] += st.size
            ent["mtime"] = max(ent["mtime"], st.mtime)
        total = sum(e["bytes"] for e in entries.values())
        out["bytes_total"] = total
        if total <= max_bytes:
            return out
        with self._lock:
            pinned = set(self._pinned)
        stat_size = {st.name: st.size for st in stats}
        # Eviction order: mtime-LRU, with PARTIAL entries sorting before
        # complete ones at equal recency — a partial is a strict subset of
        # the complete entry a future run would prefer, so it is always
        # the cheaper loss (the corpus-v2 order pin in tests/test_corpus).
        for key, ent in sorted(
            entries.items(),
            key=lambda kv: (kv[1]["mtime"], 0 if kv[1]["partial"] else 1),
        ):
            if total <= max_bytes:
                break
            # A pin protects BOTH generations of a content key: a live job
            # warmed from the partial must keep it as surely as one warmed
            # from the complete entry.
            real_key = key[len("partial-"):] if ent["partial"] else key
            if real_key in pinned:
                out["pinned_skips"] += 1
                self._count("gc_pinned_skips")
                continue
            freed = 0
            for name in ent["names"]:
                try:
                    if backend.delete(name):
                        freed += stat_size.get(name, 0)
                except OSError:
                    pass  # raced with a concurrent publish/reader: skip
            total -= freed
            out["bytes_freed"] += freed
            out["evicted"] += 1
            self._count("gc_evicted")
            self._count("gc_bytes_freed", freed)
        out["bytes_total"] = total
        return out

    # -- write side ------------------------------------------------------------

    def publish(
        self,
        key: str,
        fps: np.ndarray,
        parents: np.ndarray,
        meta: dict,
        sem_fps: Optional[np.ndarray] = None,
        sem_verdicts: Optional[np.ndarray] = None,
        complete: bool = True,
        frontier: Optional[dict] = None,
        components: Optional[dict] = None,
        journal_states: Optional[np.ndarray] = None,
        journal_depths: Optional[np.ndarray] = None,
        journal_bound: Optional[np.ndarray] = None,
    ) -> bool:
        """Publish one visited set under `key`. Complete entries are
        idempotent by content address: when an intact generation already
        exists the write is SKIPPED — that is the fleet-sharing contract
        (N replicas finishing the same key keep ONE generation, not N
        private copies) — and a successful complete publish deletes the
        partial entry it supersedes and notes the key in the family
        index. Partial entries (`complete=False`, usually with a
        `frontier` snapshot) live at a sibling path, are latest-wins (a
        longer prefix replaces a shorter one; the family index's recorded
        prefix length gates pointless re-writes), and are skipped
        entirely once a complete generation exists. Crash-atomic through
        faults/ckptio.atomic_savez (CRC32 footer, tmp/fsync/rename).
        Never raises: a publish failure (injected ``corpus.publish``
        fault or real I/O error) is counted and the job's own result is
        unaffected — degraded to unpublished, never wrong."""
        path = self.path_for(key) if complete else self.partial_path_for(key)
        if self._lease is not None and not self._lease.valid():
            # Write-side fence: a revoked replica (the zombie) must never
            # publish — not even content-identical bytes; the fence is the
            # invariant, not the content.
            self._count("lease_rejected")
            self._lease.store.count_rejected("write")
            return False
        try:
            if latest_generation(self.path_for(key)) is not None:
                # A complete generation makes both publish kinds moot: the
                # exact entry already serves every warm rung.
                self._count("publish_skipped")
                return False
            if not complete and components:
                for m in self.family_members(components.get("def", "")):
                    if (
                        m.get("key") == key
                        and not m.get("complete")
                        and int(m.get("states", 0)) >= int(len(fps))
                    ):
                        # An equal-or-longer prefix is already published;
                        # overwriting with a shorter one is sound but a
                        # strict regression — skip.
                        self._count("publish_skipped")
                        return False
            # Chaos-plane boundary: fires before the write, so a faulted
            # publish leaves no torn entry behind.
            maybe_fault("corpus.publish", key=key[:16], states=int(len(fps)))
            fps = np.asarray(fps, dtype=np.uint64)
            parents = np.asarray(parents, dtype=np.uint64)
            summary = np.zeros(
                summary_words(self.summary_log2), dtype=np.uint32
            )
            host_insert(
                summary,
                (fps & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                (fps >> np.uint64(32)).astype(np.uint32),
                self.summary_log2,
                self.summary_hashes,
            )
            names = sorted(meta.get("discoveries", {}))
            payload_extra = {}
            if sem_fps is not None and len(sem_fps):
                # The semantics plane's packed verdict table (dedup-first
                # semantics): canonical fingerprints are class-addressed,
                # so the table is valid for ANY consumer of the directory.
                payload_extra["sem_fps"] = np.asarray(
                    sem_fps, dtype=np.uint64
                )
                payload_extra["sem_verdicts"] = np.asarray(
                    sem_verdicts, dtype=np.uint8
                )
            if not complete:
                payload_extra["complete"] = np.asarray([0], dtype=np.int64)
                if frontier is not None:
                    payload_extra["f_states"] = np.asarray(
                        frontier["states"], dtype=np.uint32
                    )
                    payload_extra["f_lo"] = np.asarray(
                        frontier["lo"], dtype=np.uint32
                    )
                    payload_extra["f_hi"] = np.asarray(
                        frontier["hi"], dtype=np.uint32
                    )
                    payload_extra["f_ebits"] = np.asarray(
                        frontier["ebits"], dtype=bool
                    )
                    payload_extra["f_depths"] = np.asarray(
                        frontier["depths"], dtype=np.uint32
                    )
            if components is not None:
                payload_extra["comp"] = np.asarray(
                    [json.dumps(components)], dtype=np.str_
                )
            if (
                complete
                and journal_states is not None
                and journal_depths is not None
                and len(journal_states) == len(fps)
                and len(journal_depths) == len(fps)
            ):
                # Spec-CI journal planes (store/specdelta.py): the claimed
                # state rows in pop order + their depths + the publisher
                # boundary's verdict over them — what a later definition
                # edit re-evaluates instead of re-exploring. Misaligned
                # planes are dropped here (delta refuses, never misreads).
                payload_extra["j_states"] = np.asarray(
                    journal_states, dtype=np.uint32
                )
                payload_extra["j_depths"] = np.asarray(
                    journal_depths, dtype=np.uint32
                )
                if journal_bound is not None and len(journal_bound) == len(
                    fps
                ):
                    payload_extra["j_bound"] = np.asarray(
                        journal_bound, dtype=bool
                    )
            # Conditional write (`if_absent`): on the blob backend this is
            # a server-side If-None-Match put, so N replicas racing one
            # content key through a real object store still keep exactly
            # ONE generation — the pre-check above is just the cheap path.
            # Partial entries are the opposite contract: latest-wins, a
            # successor's longer prefix replaces its predecessor's.
            written = fenced_savez(
                path,
                {
                    "key": np.asarray([key], dtype=np.str_),
                    "format": np.asarray([FORMAT], dtype=np.int64),
                    "fps": fps,
                    "parents": parents,
                    "summary": summary,
                    **payload_extra,
                    "cfg": np.asarray(
                        [self.summary_log2, self.summary_hashes],
                        dtype=np.int64,
                    ),
                    "counts": np.asarray(
                        [
                            meta["state_count"],
                            meta["unique_count"],
                            meta["max_depth"],
                        ],
                        dtype=np.int64,
                    ),
                    "d_names": np.asarray(names, dtype=np.str_),
                    "d_fps": np.asarray(
                        [meta["discoveries"][n] for n in names],
                        dtype=np.uint64,
                    ),
                },
                lease=self._lease,
                if_absent=complete,
            )
            if written is None:
                self._count("publish_skipped")
                return False
        except LeaseRevoked:
            # The write-side fence refused a publish whose lease was
            # revoked between the pre-check above and the write — stale,
            # counted, harmless.
            self._count("lease_rejected")
            return False
        except (FaultError, OSError):
            self._count("publish_faults")
            return False
        self._count("publishes" if complete else "partial_publishes")
        if "sem_fps" in payload_extra:
            self._count("verdicts_published", int(len(payload_extra["sem_fps"])))
        if complete:
            # A complete entry supersedes the partial published under the
            # same key (if any) — delete it and drop its family row; both
            # best-effort (gc's partial-first ordering mops up a miss).
            self._supersede_partial(key, components)
        if components is not None:
            self._family_note(components, key, complete, int(fps.size))
            self._spec_note(components, key, complete, int(fps.size))
        return True

    def _supersede_partial(
        self, key: str, components: Optional[dict]
    ) -> None:
        """Delete the partial generations a complete publish supersedes
        (counted once as `superseded_entries` when anything was removed)."""
        backend = blob_backend(self.root)
        base = os.path.basename(self.partial_path_for(key))
        removed = False
        for name in (base, base + ".prev"):
            try:
                if backend.delete(name):
                    removed = True
            except OSError:
                pass  # raced with a reader/gc: the sweep gets it later
        if removed:
            self._count("superseded_entries")
            if components and "def" in components:
                self._family_drop(components["def"], key, complete=False)
            if components and components.get("core"):
                self._spec_drop(components["core"], key, complete=False)

    # -- reporting -------------------------------------------------------------

    def metrics(self) -> dict:
        """Flat counters for the obs REGISTRY "corpus" source."""
        with self._lock:
            return dict(self.counters)
