"""The ONE corpus warm-start/preload seam (ROADMAP item 4c).

Before this module each warm path was hand-wired where it existed
(`FrontierSearch.warm_start`, the service scheduler's `_maybe_warm`) and
simply absent everywhere else — the resident, sharded, and simulation
engines started cold on every job. This module is the single place the
warm-start mechanics are spelled:

- `preload_store`: seed a `TieredStore` (spill tier + Bloom summary) from a
  published `CorpusEntry`, with optional per-job salt re-keying — the
  mechanism behind every exhaustive engine's exact/near warm path.
- `preload_table`: batched insert of an entry's visited set into a
  host-side `tensor/inserts.make_table` handle — the simulation engine's
  shared visited table (and any other raw-table consumer), best-effort on
  overflow.
- The soundness ladder (`can_replay` / `can_continue`): which entry kinds
  may warm which runs. Replay of a complete entry is sound exactly when
  the publisher's run and this run would provably pop the same states in
  the same order to the same finish point — same definition, same
  batch_size, same finish policy; table packing (table_log2 /
  insert_variant / summary geometry / store kind) is free because
  membership and pop order are packing-invariant. Continuation of a
  partial entry is sound when the entry's frontier snapshot is a true
  FIFO prefix of this run (same definition, same batch_size) AND this
  run's finish policy is not already satisfied inside the prefix — the
  continuation then applies its own finish naturally, so even a
  different finish policy warm-starts (the near-partial rung).
- `frontier_chunks` / `pack_ebits`: decode a partial entry's frontier
  snapshot into the per-depth chunk runs the engines enqueue.
- `salvage_delta`: the Spec-CI rung's gate (ROADMAP item 4's definition-
  delta residue) — structural checks here, the edit classifier and the
  per-class soundness proofs in store/specdelta.py.

`knobs.WARM_KINDS` is the kind vocabulary ("exact" | "near" | "partial"
| "delta"); `knobs.check_registry()` pins every engine's
`WARM_KINDS`/`WARM_SEAM` aliases against this module so the warm knob
stays defined exactly once.

Deliberately jax-free at import time (knobs.check_registry probes the
alias on jax-free images): the one salted-table path imports lazily.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..knobs import WARM_KINDS

__all__ = [
    "WARM_KINDS",
    "preload_store",
    "preload_table",
    "can_replay",
    "can_continue",
    "salvage_delta",
    "frontier_chunks",
    "pack_ebits",
]

_M32 = np.uint64(0xFFFFFFFF)


def split_fps(fps) -> tuple:
    """uint64[n] packed fingerprints -> (lo, hi) uint32[n] halves."""
    fps = np.asarray(fps, dtype=np.uint64)
    return (fps & _M32).astype(np.uint32), (fps >> np.uint64(32)).astype(
        np.uint32
    )


def preload_store(
    store, entry, salt_lo=None, salt_hi=None, use_summary: bool = True,
    mask=None,
) -> int:
    """Seed a TieredStore's spill tier + Bloom summary from a corpus entry
    (the exact/near/partial warm mechanism for every exhaustive engine).
    Salted callers (service jobs) re-key the set per job; unsalted callers
    with a matching summary geometry take the serialized-summary fast
    path. `mask` restricts the preload to a row subset — the sharded
    engine's per-owner split (the FULL entry summary is still OR-ed in:
    a superset Bloom is sound, each shard only ever probes states it
    owns, and extra bits at worst cost a false suspect resolved exactly
    against that shard's spill tier). Returns states preloaded."""
    fps, parents = entry.fps, entry.parents
    if mask is not None:
        mask = np.asarray(mask, dtype=bool)
        fps = np.asarray(fps, dtype=np.uint64)[mask]
        parents = np.asarray(parents, dtype=np.uint64)[mask]
    return store.preload(
        fps,
        parents,
        salt_lo=salt_lo,
        salt_hi=salt_hi,
        summary_words_arr=entry.summary if use_summary else None,
        summary_cfg=(entry.summary_log2, entry.summary_hashes),
    )


def preload_table(table, fps, parents, salt: int = 0, batch: int = 4096) -> int:
    """Batched insert of packed unsalted (fps, parents) into a host-side
    `tensor/inserts.make_table` handle — the simulation engine's shared
    visited table warm path. `salt` re-keys through `job_salt` exactly as
    the engine's own inserts do (root parents survive as 0, preserving the
    chain-walk sentinel). Best-effort: stops at table overflow (a partial
    preload only costs coverage accounting, never correctness). Returns
    states actually inserted as new."""
    import jax.numpy as jnp

    from ..tensor.fingerprint import job_salt, salt_fp

    fps = np.asarray(fps, dtype=np.uint64)
    parents = np.asarray(parents, dtype=np.uint64)
    if fps.size == 0:
        return 0
    lo, hi = split_fps(fps)
    plo, phi = split_fps(parents)
    if salt:
        s_lo, s_hi = job_salt(salt)
        lo, hi = salt_fp(lo, hi, s_lo, s_hi)
        root = (plo == 0) & (phi == 0)
        plo, phi = salt_fp(plo, phi, s_lo, s_hi)
        plo = np.where(root, np.uint32(0), plo).astype(np.uint32)
        phi = np.where(root, np.uint32(0), phi).astype(np.uint32)
    inserted = 0
    n = int(fps.size)
    for b0 in range(0, n, batch):
        b1 = min(b0 + batch, n)
        m = b1 - b0
        pad = [np.zeros(batch, dtype=np.uint32) for _ in range(4)]
        for p, a in zip(pad, (lo, hi, plo, phi)):
            p[:m] = a[b0:b1]
        res = table.insert(
            jnp.asarray(pad[0]),
            jnp.asarray(pad[1]),
            jnp.asarray(pad[2]),
            jnp.asarray(pad[3]),
            jnp.asarray(np.arange(batch) < m),
        )
        inserted += int(np.asarray(res.is_new).sum())
        if bool(res.overflow):
            break  # best-effort coverage: stop, never raise
    return inserted


def _finish_repr(finish_sig) -> str:
    """Stable string form of a corpus.finish_signature tuple (the family
    index stores strings; repr of the tuple is deterministic)."""
    return repr(tuple(finish_sig))


def can_replay(entry, batch_size: int, finish_sig) -> bool:
    """True when `entry` (complete) may be replayed verbatim as this run's
    result: same batch_size and same finish signature — pop/claim order
    and the finish point are then provably identical, and everything else
    (table packing) is result-invariant. The "exact" and "near" rungs."""
    if not getattr(entry, "complete", True):
        return False
    comp = getattr(entry, "components", None) or {}
    return (
        int(comp.get("batch_size", -1)) == int(batch_size)
        and comp.get("finish") == _finish_repr(finish_sig)
    )


def can_continue(
    entry,
    batch_size: int,
    finish_when,
    properties,
    target_state_count: Optional[int] = None,
    target_max_depth: Optional[int] = None,
) -> bool:
    """True when `entry` (partial, with a frontier snapshot) may seed this
    run as a FIFO prefix: same batch_size (chunk/batch boundaries must
    reproduce), any finish policy — PROVIDED the prefix has not already
    passed this run's finish point (a finish satisfied inside the prefix
    means the cold run would have stopped earlier with smaller counts, so
    the continuation must decline and run cold). `properties` is the
    model's property list (HasDiscoveries.matches needs it)."""
    if getattr(entry, "complete", True):
        return False
    if getattr(entry, "frontier", None) is None:
        return False  # coverage-only entry (e.g. simulation): no prefix
    comp = getattr(entry, "components", None) or {}
    if int(comp.get("batch_size", -1)) != int(batch_size):
        return False
    meta = entry.meta
    disc = set(meta.get("discoveries", {}))
    props = list(properties)
    if props and len(disc) >= len(props):
        return False  # every property already discovered inside the prefix
    if finish_when is not None and finish_when.matches(props, disc):
        return False
    if target_state_count is not None and int(
        meta.get("state_count", 0)
    ) >= int(target_state_count):
        return False
    if target_max_depth is not None and int(
        meta.get("max_depth", 0)
    ) >= int(target_max_depth):
        return False
    return True


def salvage_delta(
    entry,
    model,
    new_comps: dict,
    batch_size: int,
    finish_when,
    target_state_count: Optional[int] = None,
    target_max_depth: Optional[int] = None,
):
    """The Spec-CI rung's gate (knobs.WARM_KINDS "delta"): may `entry` —
    published under a DIFFERENT definition hash of the same geometry —
    warm this run? Structural soundness lives here, mirroring
    `can_replay`/`can_continue`: the entry must be complete (the salvage
    proofs are exhaustion arguments) and share this run's batch_size
    (pop/claim order must reproduce). The edit classifier and the
    per-class salvage rules live in store/specdelta.py (lazily imported:
    salvage re-traces and re-evaluates jaxprs, and this module stays
    jax-free at import time).

    Returns ``(delta_class, servable_entry_or_None)``: a complete entry
    replays verbatim (verdicts already re-evaluated into its meta), a
    partial one continues from the re-derived frontier (the caller must
    mark the job no-publish — a widened continuation's traversal-order
    statistics are not cold-bit-identical), and ``None`` refuses —
    counted by the caller as `delta_refusals`, provably cold."""
    from . import specdelta

    old_comps = (getattr(entry, "components", None) or {}).get("comps")
    delta_class = specdelta.classify(new_comps, old_comps)
    if delta_class not in ("properties-only", "boundary-only"):
        return delta_class, None
    if not getattr(entry, "complete", True):
        return delta_class, None
    comp = getattr(entry, "components", None) or {}
    if int(comp.get("batch_size", -1)) != int(batch_size):
        return delta_class, None
    return delta_class, specdelta.salvage(
        entry, model, delta_class, finish_when,
        target_state_count, target_max_depth, new_comps,
    )


def pack_ebits(ebits: np.ndarray) -> np.ndarray:
    """bool[n, P] pending-eventually bits -> uint32[n] bitmask rows (the
    device-resident engines' in-queue encoding)."""
    ebits = np.asarray(ebits, dtype=bool)
    n, p = ebits.shape
    out = np.zeros(n, dtype=np.uint32)
    for i in range(p):
        out |= ebits[:, i].astype(np.uint32) << np.uint32(i)
    return out


def frontier_chunks(entry) -> list:
    """Decode a partial entry's frontier snapshot into per-depth runs
    [(states u32[m,L], lo u32[m], hi u32[m], ebits bool[m,P], depth int)]
    in FIFO order — depths in a snapshot are monotonically nondecreasing,
    so contiguous equal-depth runs are exactly the engines' chunk shape."""
    f = entry.frontier
    if f is None or f["lo"].size == 0:
        return []
    depths = np.asarray(f["depths"])
    out = []
    start = 0
    n = int(depths.size)
    for i in range(1, n + 1):
        if i == n or depths[i] != depths[start]:
            sl = slice(start, i)
            out.append(
                (
                    np.asarray(f["states"][sl], dtype=np.uint32),
                    np.asarray(f["lo"][sl], dtype=np.uint32),
                    np.asarray(f["hi"][sl], dtype=np.uint32),
                    np.asarray(f["ebits"][sl], dtype=bool),
                    int(depths[start]),
                )
            )
            start = i
    return out
