"""Tiered state store: device-resident hot set + host spill tier.

The device engines' visited set is an HBM hash table (tensor/hashtable.py);
any state space bigger than the table used to end in an overflow abort. This
package converts that hard wall into graceful degradation, the
memory-hierarchy move every at-scale explicit-state checker makes (Stern &
Dill's disk-based Murphi, TLC's disk fingerprint sets) translated to the TPU
hierarchy: HBM stays the hot tier, host RAM is the cold tier, and a
device-resident Bloom-style summary of the spilled set keeps the common
probe path on device.

Pieces:

- `summary` — the Bloom summary: uint32 bit words probed inside the jitted
  engine step (`maybe_contains`), populated on host at eviction time
  (`host_insert`; no false negatives, tunable false-positive rate via
  `summary_log2`).
- `host` — `HostSpillStore`: the cold tier. Packed uint64 fingerprint +
  parent arrays, appended at eviction, merge-compacted (sorted, first-writer
  dedup) on a background thread; exact membership via binary search.
- `tiered` — `TieredStore`: the orchestration the engines call between
  device dispatches: high/low-water eviction of COLD, NON-FULL buckets
  (full buckets anchor probe chains and are never evicted — see
  tiered.py for the safety argument), suspect resolution, per-tier
  counters, checkpoint serialization.

- `corpus` — `CorpusStore`: the cross-job warm-start corpus (ROADMAP item
  4): completed jobs publish their visited set (packed host-tier arrays +
  a serialized Bloom summary) as content-addressed, CRC-checked ckptio
  generations; a later submission with the same content key preloads the
  corpus into the spill tier + summary (`TieredStore.preload`) and known
  states dedup-filter on device before expansion.

Engines opt in with `store="tiered"` (FrontierSearch / ResidentSearch /
ShardedSearch, and through `spawn_tpu(store="tiered", ...)`); the corpus
is wired through `CheckService(corpus_dir=...)` / `ServiceFleet` and
`FrontierSearch.warm_start`.
"""

from .corpus import CorpusEntry, CorpusStore, content_key, model_def_hash
from .host import HostSpillStore
from .summary import host_insert, maybe_contains, summary_words
from .tiered import TieredConfig, TieredStore

__all__ = [
    "CorpusEntry",
    "CorpusStore",
    "HostSpillStore",
    "TieredConfig",
    "TieredStore",
    "content_key",
    "host_insert",
    "maybe_contains",
    "model_def_hash",
    "summary_words",
]
