"""Tiered-store orchestration: high/low-water eviction, suspect resolution.

`TieredStore` is the piece the engines talk to between device dispatches. It
owns the host spill tier (`HostSpillStore`), the Bloom summary words (numpy
master copy; `device_summary()` hands the engines a device-resident mirror),
a sweep pointer, and the per-tier counters the bench/Explorer surface.

Eviction policy — the part that must not break the insert kernel:

The visited-table insert (tensor/hashtable.py) resolves bucket overflow by
linear probing to the next bucket, and its membership argument is "a key
absent from the first NON-FULL bucket of its chain is absent". A bucket
only ever sends a key onward when it has no free slot — i.e. when it is
full — and, outside eviction, slots are never emptied; so a bucket that
ever overflowed a key is full at that moment and stays full unless eviction
empties it. Therefore: **eviction only ever empties buckets that are
currently non-full**. Such a bucket never overflowed anything, no probe
chain passes THROUGH it relying on its fullness, and emptying it merely
moves its keys' membership duty to the spill tier — where the Bloom summary
(no false negatives) plus the host store's exact check pick it up. Full
buckets are pinned on device forever; at sane water marks they are a thin
binomial tail of the table.

The sweep is a clock hand over buckets: each spill event walks windows from
the pointer, evicting every non-full, non-empty bucket, until occupancy is
back under the LOW water mark (hysteresis — one eviction buys many steps of
headroom) or a full cycle found nothing more to free (every remaining
bucket full: the caller surfaces that as a real capacity error instead of
spinning).

Two eviction entry points share the same per-window core: `evict` takes
device arrays and pulls only window-sized slices over PCIe (async
device-to-host copies, one contiguous dynamic_update_slice write-back per
array), for the single-device engines; `evict_host` takes whole numpy
tables, for the sharded engine's service path (which has already gathered
the carry to host) and for tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..tensor.fingerprint import pack_fp
from ..tensor.hashtable import BUCKET
from .host import HostSpillStore
from .summary import DEFAULT_HASHES, host_insert, summary_words


_WRITE4 = None


def _window_writeback():
    """Module-cached jitted window write-back (one contiguous
    dynamic_update_slice per table array) — built lazily so importing the
    store never initializes a device backend."""
    global _WRITE4
    if _WRITE4 is None:
        import jax

        @jax.jit
        def write4(tl, th, pl, ph, wl, wh, wpl, wph, start):
            upd = lambda t, w: jax.lax.dynamic_update_slice(t, w, (start,))
            return upd(tl, wl), upd(th, wh), upd(pl, wpl), upd(ph, wph)

        _WRITE4 = write4
    return _WRITE4


@dataclass(frozen=True)
class TieredConfig:
    """Knobs for the tiered store (reachable via engine kwargs and
    `spawn_tpu(store="tiered", high_water=..., summary_log2=...)`).

    high_water: hot-tier fill fraction (claimed slots / table slots) that
        triggers a spill event.
    low_water: eviction target fill; defaults to high_water - 0.25
        (floored at 0.1) — the hysteresis band that keeps spill events rare.
    summary_log2: log2 of the Bloom summary BIT count. False-positive rate
        with k hashes and n spilled states is ~(1 - e^(-kn/m))^k; at the
        default k=4, m = 64x the spilled count gives ~0.24% — size it ~6
        bits per expected spilled state.
    summary_hashes: Bloom probe count k.
    sweep_buckets: eviction window size in buckets (per device round-trip);
        defaults to n_buckets/8 (>= 1).
    """

    high_water: float = 0.85
    low_water: Optional[float] = None
    summary_log2: int = 20
    summary_hashes: int = DEFAULT_HASHES
    sweep_buckets: Optional[int] = None

    def resolved_low_water(self) -> float:
        if self.low_water is not None:
            if not 0.0 < self.low_water < self.high_water:
                raise ValueError(
                    "low_water must be in (0, high_water) "
                    f"(got {self.low_water} vs high {self.high_water})"
                )
            return self.low_water
        return max(0.1, self.high_water - 0.25)

    def validate(self) -> None:
        if not 0.0 < self.high_water <= 1.0:
            raise ValueError(f"high_water must be in (0, 1], got {self.high_water}")
        self.resolved_low_water()
        summary_words(self.summary_log2)  # raises on < 5


class TieredStore:
    def __init__(
        self,
        table_size: int,
        config: TieredConfig = TieredConfig(),
        background: bool = True,
    ):
        config.validate()
        self.config = config
        self.size = table_size
        self.bucket = min(BUCKET, table_size)
        self.n_buckets = table_size // self.bucket
        self.high_slots = max(int(config.high_water * table_size), 1)
        self.low_slots = int(config.resolved_low_water() * table_size)
        self.window = config.sweep_buckets or max(self.n_buckets // 8, 1)
        self.summary_np = np.zeros(
            summary_words(config.summary_log2), dtype=np.uint32
        )
        self.store = HostSpillStore(background=background)
        self.sweep = 0
        self.spill_events = 0
        self.suspects_checked = 0
        self.suspects_dup = 0
        self._summary_dev = None

    # -- device summary mirror -------------------------------------------------

    def device_summary(self):
        """The Bloom words as a device array (cached; refreshed after each
        spill event). Engines pass it into their jitted step."""
        if self._summary_dev is None:
            import jax.numpy as jnp

            self._summary_dev = jnp.asarray(self.summary_np)
        return self._summary_dev

    # -- eviction --------------------------------------------------------------

    def _evict_window(self, win_lo, win_hi, win_plo, win_phi):
        """Core shared by both entry points: given one window of bucket rows
        ([w, bucket] numpy views), empty every non-full, non-empty bucket.
        Mutates the window arrays in place; returns the evicted count."""
        full = (win_lo != 0).all(axis=1)
        occupied = win_lo != 0
        evictable = (~full)[:, None] & occupied
        n = int(evictable.sum())
        if n == 0:
            return 0
        ev_lo = win_lo[evictable]
        ev_hi = win_hi[evictable]
        ev_plo = win_plo[evictable]
        ev_phi = win_phi[evictable]
        host_insert(
            self.summary_np, ev_lo, ev_hi,
            self.config.summary_log2, self.config.summary_hashes,
        )
        self.store.append(pack_fp(ev_lo, ev_hi), pack_fp(ev_plo, ev_phi))
        for w in (win_lo, win_hi, win_plo, win_phi):
            w[evictable] = 0
        return n

    def evict_host(self, t_lo, t_hi, p_lo, p_hi, hot_claims: int) -> int:
        """Numpy-table eviction (sharded service path + tests): sweep until
        occupancy <= low water or a full cycle frees nothing. Mutates the
        arrays in place; returns the evicted slot count."""
        target = hot_claims - self.low_slots
        if target <= 0:
            return 0
        b = self.bucket
        freed = 0
        scanned = 0
        while freed < target and scanned < self.n_buckets:
            w = min(self.window, self.n_buckets - self.sweep)
            s0 = self.sweep * b
            s1 = s0 + w * b
            freed += self._evict_window(
                t_lo[s0:s1].reshape(w, b),
                t_hi[s0:s1].reshape(w, b),
                p_lo[s0:s1].reshape(w, b),
                p_hi[s0:s1].reshape(w, b),
            )
            scanned += w
            self.sweep = (self.sweep + w) % self.n_buckets
        if freed:
            self.spill_events += 1
            self._summary_dev = None
        return freed

    def evict(self, t_lo, t_hi, p_lo, p_hi, hot_claims: int):
        """Device-array eviction: pull window slices host-side (async
        copies), run the shared core, write kept rows back with one
        contiguous dynamic_update_slice per array. Returns
        (t_lo, t_hi, p_lo, p_hi, evicted_count) with fresh device arrays."""
        import jax.numpy as jnp

        target = hot_claims - self.low_slots
        if target <= 0:
            return t_lo, t_hi, p_lo, p_hi, 0

        write4 = _window_writeback()
        b = self.bucket
        freed = 0
        scanned = 0
        while freed < target and scanned < self.n_buckets:
            w = min(self.window, self.n_buckets - self.sweep)
            s0 = self.sweep * b
            s1 = s0 + w * b
            slices = [a[s0:s1] for a in (t_lo, t_hi, p_lo, p_hi)]
            for s in slices:
                s.copy_to_host_async()
            # np.array (not asarray): device buffers surface as read-only
            # views and the window core mutates in place.
            wins = [np.array(s).reshape(w, b) for s in slices]
            n = self._evict_window(*wins)
            if n:
                t_lo, t_hi, p_lo, p_hi = write4(
                    t_lo, t_hi, p_lo, p_hi,
                    *(jnp.asarray(x.reshape(-1)) for x in wins),
                    jnp.int32(s0),
                )
                freed += n
            scanned += w
            self.sweep = (self.sweep + w) % self.n_buckets
        if freed:
            self.spill_events += 1
            self._summary_dev = None
        return t_lo, t_hi, p_lo, p_hi, freed

    # -- suspect resolution ----------------------------------------------------

    def resolve_suspects(self, lo, hi) -> np.ndarray:
        """bool[n]: True where the suspect fingerprint IS a spilled
        duplicate (drop it); False where the Bloom hit was a false positive
        (the state is genuinely new — enqueue it)."""
        lo = np.asarray(lo)
        hi = np.asarray(hi)
        dup = self.store.contains(pack_fp(lo, hi))
        self.suspects_checked += int(lo.size)
        self.suspects_dup += int(dup.sum())
        return dup

    def close(self) -> None:
        """Release the spill tier's background compactor (see
        HostSpillStore.close) — called whenever an engine replaces its
        store (reset / checkpoint restore)."""
        self.store.close()

    # -- reporting / reconstruction -------------------------------------------

    def stats(self, hot_claims: int) -> dict:
        """The per-tier counters the bench detail and Explorer surface."""
        return {
            "store": "tiered",
            "hot_fill": round(hot_claims / max(self.size, 1), 4),
            "spilled_states": len(self.store),
            "spill_events": self.spill_events,
            "suspects_checked": self.suspects_checked,
            "suspects_dup": self.suspects_dup,
        }

    def parent_map(self) -> dict:
        return self.store.parent_map()

    # -- checkpoint ------------------------------------------------------------

    def to_checkpoint(self) -> dict:
        """Arrays for the engine checkpoint (the summary is NOT serialized:
        it is a pure function of the spilled fingerprints and is rebuilt on
        load — smaller files, and summary_log2 can even change on resume)."""
        fps, parents = self.store.to_arrays()
        return {"spill_fps": fps, "spill_parents": parents}

    def meta(self) -> dict:
        c = self.config
        return {
            "high_water": c.high_water,
            "low_water": c.resolved_low_water(),
            "summary_log2": c.summary_log2,
            "summary_hashes": c.summary_hashes,
            "spill_events": self.spill_events,
        }

    @classmethod
    def from_checkpoint(
        cls,
        table_size: int,
        meta: dict,
        spill_fps: np.ndarray,
        spill_parents: np.ndarray,
        background: bool = True,
    ) -> "TieredStore":
        cfg = TieredConfig(
            high_water=meta["high_water"],
            low_water=meta["low_water"],
            summary_log2=meta["summary_log2"],
            summary_hashes=meta["summary_hashes"],
        )
        ts = cls(table_size, cfg, background=background)
        fps = np.asarray(spill_fps, dtype=np.uint64)
        ts.store.close()  # replaced wholesale below
        ts.store = HostSpillStore.from_arrays(
            fps, spill_parents, background=background
        )
        ts.spill_events = int(meta.get("spill_events", 0))
        host_insert(
            ts.summary_np,
            (fps & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            (fps >> np.uint64(32)).astype(np.uint32),
            cfg.summary_log2,
            cfg.summary_hashes,
        )
        return ts
