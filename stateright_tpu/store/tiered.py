"""Tiered-store orchestration: high/low-water eviction, suspect resolution.

`TieredStore` is the piece the engines talk to between device dispatches. It
owns the host spill tier (`HostSpillStore`), the Bloom summary words (numpy
master copy; `device_summary()` hands the engines a device-resident mirror),
a sweep pointer, and the per-tier counters the bench/Explorer surface.

Eviction policy — the part that must not break the insert kernel:

The visited-table insert (tensor/hashtable.py) resolves bucket overflow by
linear probing to the next bucket, and its membership argument is "a key
absent from the first NON-FULL bucket of its chain is absent". A bucket
only ever sends a key onward when it has no free slot — i.e. when it is
full — and, outside eviction, slots are never emptied; so a bucket that
ever overflowed a key is full at that moment and stays full unless eviction
empties it. Therefore: **eviction only ever empties buckets that are
currently non-full**. Such a bucket never overflowed anything, no probe
chain passes THROUGH it relying on its fullness, and emptying it merely
moves its keys' membership duty to the spill tier — where the Bloom summary
(no false negatives) plus the host store's exact check pick it up. Full
buckets are pinned on device forever; at sane water marks they are a thin
binomial tail of the table.

The sweep is a clock hand over buckets: each spill event walks windows from
the pointer, evicting every non-full, non-empty bucket, until occupancy is
back under the LOW water mark (hysteresis — one eviction buys many steps of
headroom) or a full cycle found nothing more to free (every remaining
bucket full: the caller surfaces that as a real capacity error instead of
spinning).

Two eviction entry points share the same per-window core: `evict` takes
device arrays and pulls only window-sized slices over PCIe (async
device-to-host copies, one contiguous dynamic_update_slice write-back per
array), for the single-device engines; `evict_host` takes whole numpy
tables, for the sharded engine's service path (which has already gathered
the carry to host) and for tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..faults.plan import maybe_fault
from ..tensor.fingerprint import pack_fp, salt_fp
from ..tensor.hashtable import BUCKET
from .host import HostSpillStore
from .summary import DEFAULT_HASHES, host_insert, summary_words


_WINDOW_OPS = None


def _window_ops():
    """Module-cached jitted eviction kernels — built lazily so importing
    the store never initializes a device backend.

    Device-side PRE-FILTER (the ROUND7 open item): instead of pulling a
    whole eviction window over PCIe and inspecting it on host, the device
    first counts occupied slots per bucket (one tiny [w]-int transfer),
    the host picks the evictable buckets (non-full, non-empty) from the
    counts alone, and only THOSE bucket rows are gathered across PCIe.
    Evicted buckets are then zeroed in place on device — no write-back
    traffic at all. At high pin rates (many full buckets) this cuts the
    moved volume from 4 arrays x window to 4 arrays x evictable subset;
    the `evict_bytes_pcie` / `evict_bytes_unfiltered` counters prove the
    reduction per run."""
    global _WINDOW_OPS
    if _WINDOW_OPS is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnums=(2, 3))
        def count_window(t_lo, start, w, b):
            win = jax.lax.dynamic_slice(t_lo, (start,), (w * b,))
            return (win.reshape(w, b) != 0).sum(axis=1, dtype=jnp.int32)

        @partial(jax.jit, static_argnums=(4,))
        def gather_buckets(t_lo, t_hi, p_lo, p_hi, b, idx):
            def g(a):
                return a.reshape(-1, b)[idx]

            return g(t_lo), g(t_hi), g(p_lo), g(p_hi)

        @partial(jax.jit, static_argnums=(4,))
        def zero_buckets(t_lo, t_hi, p_lo, p_hi, b, idx):
            def z(a):
                shape = a.shape
                return (
                    a.reshape(-1, b)
                    .at[idx]
                    .set(jnp.uint32(0), mode="drop")
                    .reshape(shape)
                )

            return z(t_lo), z(t_hi), z(p_lo), z(p_hi)

        _WINDOW_OPS = (count_window, gather_buckets, zero_buckets)
    return _WINDOW_OPS


@dataclass(frozen=True)
class TieredConfig:
    """Knobs for the tiered store (reachable via engine kwargs and
    `spawn_tpu(store="tiered", high_water=..., summary_log2=...)`).

    high_water: hot-tier fill fraction (claimed slots / table slots) that
        triggers a spill event.
    low_water: eviction target fill; defaults to high_water - 0.25
        (floored at 0.1) — the hysteresis band that keeps spill events rare.
    summary_log2: log2 of the Bloom summary BIT count. False-positive rate
        with k hashes and n spilled states is ~(1 - e^(-kn/m))^k; at the
        default k=4, m = 64x the spilled count gives ~0.24% — size it ~6
        bits per expected spilled state.
    summary_hashes: Bloom probe count k.
    sweep_buckets: eviction window size in buckets (per device round-trip);
        defaults to n_buckets/8 (>= 1).
    """

    high_water: float = 0.85
    low_water: Optional[float] = None
    summary_log2: int = 20
    summary_hashes: int = DEFAULT_HASHES
    sweep_buckets: Optional[int] = None

    def resolved_low_water(self) -> float:
        if self.low_water is not None:
            if not 0.0 < self.low_water < self.high_water:
                raise ValueError(
                    "low_water must be in (0, high_water) "
                    f"(got {self.low_water} vs high {self.high_water})"
                )
            return self.low_water
        return max(0.1, self.high_water - 0.25)

    def validate(self) -> None:
        if not 0.0 < self.high_water <= 1.0:
            raise ValueError(f"high_water must be in (0, 1], got {self.high_water}")
        self.resolved_low_water()
        summary_words(self.summary_log2)  # raises on < 5


class TieredStore:
    def __init__(
        self,
        table_size: int,
        config: TieredConfig = TieredConfig(),
        background: bool = True,
    ):
        config.validate()
        self.config = config
        self.size = table_size
        self.bucket = min(BUCKET, table_size)
        self.n_buckets = table_size // self.bucket
        self.high_slots = max(int(config.high_water * table_size), 1)
        self.low_slots = int(config.resolved_low_water() * table_size)
        self.window = config.sweep_buckets or max(self.n_buckets // 8, 1)
        self.summary_np = np.zeros(
            summary_words(config.summary_log2), dtype=np.uint32
        )
        self.store = HostSpillStore(background=background)
        self.sweep = 0
        self.spill_events = 0
        self.suspects_checked = 0
        self.suspects_dup = 0
        # PCIe accounting for the device-side eviction pre-filter: bytes
        # actually moved device→host (bucket counts + evictable rows) vs
        # what the unfiltered full-window transfer would have moved.
        self.evict_bytes_pcie = 0
        self.evict_bytes_unfiltered = 0
        self._summary_dev = None

    # -- device summary mirror -------------------------------------------------

    def device_summary(self):
        """The Bloom words as a device array (cached; refreshed after each
        spill event). Engines pass it into their jitted step."""
        if self._summary_dev is None:
            import jax.numpy as jnp

            self._summary_dev = jnp.asarray(self.summary_np)
        return self._summary_dev

    # -- eviction --------------------------------------------------------------

    def _evict_window(self, win_lo, win_hi, win_plo, win_phi):
        """Core shared by both entry points: given one window of bucket rows
        ([w, bucket] numpy views), empty every non-full, non-empty bucket.
        Mutates the window arrays in place; returns the evicted count."""
        full = (win_lo != 0).all(axis=1)
        occupied = win_lo != 0
        evictable = (~full)[:, None] & occupied
        n = int(evictable.sum())
        if n == 0:
            return 0
        ev_lo = win_lo[evictable]
        ev_hi = win_hi[evictable]
        ev_plo = win_plo[evictable]
        ev_phi = win_phi[evictable]
        host_insert(
            self.summary_np, ev_lo, ev_hi,
            self.config.summary_log2, self.config.summary_hashes,
        )
        self.store.append(pack_fp(ev_lo, ev_hi), pack_fp(ev_plo, ev_phi))
        for w in (win_lo, win_hi, win_plo, win_phi):
            w[evictable] = 0
        return n

    def evict_host(self, t_lo, t_hi, p_lo, p_hi, hot_claims: int) -> int:
        """Numpy-table eviction (sharded service path + tests): sweep until
        occupancy <= low water or a full cycle frees nothing. Mutates the
        arrays in place; returns the evicted slot count."""
        target = hot_claims - self.low_slots
        if target <= 0:
            return 0
        # Chaos-plane boundary: a spill-tier I/O fault fires before any
        # bucket is emptied, so the tables stay sound (faults/plan.py).
        maybe_fault("store.spill", tier="host", target=target)
        b = self.bucket
        freed = 0
        scanned = 0
        while freed < target and scanned < self.n_buckets:
            w = min(self.window, self.n_buckets - self.sweep)
            s0 = self.sweep * b
            s1 = s0 + w * b
            freed += self._evict_window(
                t_lo[s0:s1].reshape(w, b),
                t_hi[s0:s1].reshape(w, b),
                p_lo[s0:s1].reshape(w, b),
                p_hi[s0:s1].reshape(w, b),
            )
            scanned += w
            self.sweep = (self.sweep + w) % self.n_buckets
        if freed:
            self.spill_events += 1
            self._summary_dev = None
        return freed

    def evict(self, t_lo, t_hi, p_lo, p_hi, hot_claims: int):
        """Device-array eviction with the device-side pre-filter: per
        window, transfer only the per-bucket occupancy counts (tiny), pick
        evictable buckets (non-full, non-empty) on host from the counts,
        gather ONLY those bucket rows over PCIe, and zero them in place on
        device — full (pinned) buckets never cross the bus and nothing is
        written back. Returns (t_lo, t_hi, p_lo, p_hi, evicted_count) with
        fresh device arrays."""
        import jax.numpy as jnp

        target = hot_claims - self.low_slots
        if target <= 0:
            return t_lo, t_hi, p_lo, p_hi, 0
        # Chaos-plane boundary: fires before any PCIe transfer or device
        # zeroing, so a faulted eviction leaves the tables untouched.
        maybe_fault("store.spill", tier="device", target=target)

        count_window, gather_buckets, zero_buckets = _window_ops()
        b = self.bucket
        freed = 0
        scanned = 0
        while freed < target and scanned < self.n_buckets:
            w = min(self.window, self.n_buckets - self.sweep)
            s0 = self.sweep * b
            counts = np.asarray(
                count_window(t_lo, jnp.int32(s0), w, b)
            )
            self.evict_bytes_pcie += counts.nbytes
            self.evict_bytes_unfiltered += 4 * w * b * 4  # 4 u32 arrays
            evictable = (counts > 0) & (counts < b)
            n = int(counts[evictable].sum())
            if n:
                idx = (np.nonzero(evictable)[0] + self.sweep).astype(np.int32)
                # Pad the gather to the next power of two so the jit cache
                # holds O(log window) shapes, not one per eviction event;
                # padding repeats row 0 of the selection (sliced off below).
                n_sel = len(idx)
                n_pad = 1 << max(n_sel - 1, 0).bit_length()
                idx_pad = np.full(n_pad, idx[0], dtype=np.int32)
                idx_pad[:n_sel] = idx
                rows = gather_buckets(
                    t_lo, t_hi, p_lo, p_hi, b, jnp.asarray(idx_pad)
                )
                wins = [np.array(r)[:n_sel] for r in rows]
                self.evict_bytes_pcie += 4 * n_pad * b * 4
                n_host = self._evict_window(*wins)
                # The gathered rows are exactly the evictable buckets, so
                # the host core must free precisely the counted slots.
                assert n_host == n, (n_host, n)
                t_lo, t_hi, p_lo, p_hi = zero_buckets(
                    t_lo, t_hi, p_lo, p_hi, b, jnp.asarray(idx_pad)
                )
                freed += n
            scanned += w
            self.sweep = (self.sweep + w) % self.n_buckets
        if freed:
            self.spill_events += 1
            self._summary_dev = None
        return t_lo, t_hi, p_lo, p_hi, freed

    # -- warm-start preload (store/corpus.py) ----------------------------------

    def preload(
        self,
        fps,
        parents,
        salt_lo=None,
        salt_hi=None,
        summary_words_arr: Optional[np.ndarray] = None,
        summary_cfg: Optional[tuple] = None,
    ) -> int:
        """Seed the spill tier + Bloom summary with a PUBLISHED visited set
        (packed unsalted uint64 fps/parents — the corpus entry shape, which
        is by construction the host tier's own shape) BEFORE the engine's
        first step, so every known state is dedup-filtered on device at its
        first re-appearance and resolved exactly on host via the normal r7
        suspect path.

        `salt_lo`/`salt_hi` re-key the set for a service job (the spill
        tier stores TABLE keys; salting is what keeps one job's preloaded
        states from shadowing a co-resident job's) — root parents (0)
        survive salting as 0, preserving the chain-walk sentinel. Unsalted
        callers (standalone engines) that pass the entry's serialized
        Bloom `summary_words_arr` with a matching `summary_cfg` get the
        fast path: the words are OR-ed straight into the summary instead
        of re-hashing every fingerprint. Returns the state count
        preloaded."""
        fps = np.asarray(fps, dtype=np.uint64)
        parents = np.asarray(parents, dtype=np.uint64)
        if fps.size == 0:
            return 0
        m32 = np.uint64(0xFFFFFFFF)
        lo = (fps & m32).astype(np.uint32)
        hi = (fps >> np.uint64(32)).astype(np.uint32)
        plo = (parents & m32).astype(np.uint32)
        phi = (parents >> np.uint64(32)).astype(np.uint32)
        salted = salt_lo is not None and salt_hi is not None and (
            int(salt_lo) or int(salt_hi)
        )
        if salted:
            lo, hi = salt_fp(lo, hi, salt_lo, salt_hi)
            root = (plo == 0) & (phi == 0)
            plo, phi = salt_fp(plo, phi, salt_lo, salt_hi)
            plo = np.where(root, np.uint32(0), plo).astype(np.uint32)
            phi = np.where(root, np.uint32(0), phi).astype(np.uint32)
        cfg = (self.config.summary_log2, self.config.summary_hashes)
        if (
            not salted
            and summary_words_arr is not None
            and summary_cfg == cfg
            and summary_words_arr.size == self.summary_np.size
        ):
            # Serialized-summary fast path: the publisher already hashed
            # every fingerprint at this exact geometry.
            np.bitwise_or(
                self.summary_np,
                np.asarray(summary_words_arr, dtype=np.uint32),
                out=self.summary_np,
            )
        else:
            host_insert(self.summary_np, lo, hi, *cfg)
        # The spill tier dedups by first writer, so re-preloading the same
        # (salted) set — a requeued job re-admitted on the same replica —
        # costs one compaction, not duplicate membership.
        self.store.append(pack_fp(lo, hi), pack_fp(plo, phi))
        self._summary_dev = None
        return int(fps.size)

    # -- suspect resolution ----------------------------------------------------

    def resolve_suspects(self, lo, hi) -> np.ndarray:
        """bool[n]: True where the suspect fingerprint IS a spilled
        duplicate (drop it); False where the Bloom hit was a false positive
        (the state is genuinely new — enqueue it)."""
        lo = np.asarray(lo)
        hi = np.asarray(hi)
        # Chaos-plane boundary: exact-membership reads can fault too (the
        # spill tier is the component designed to sit on slower storage).
        maybe_fault("store.resolve", suspects=int(lo.size))
        dup = self.store.contains(pack_fp(lo, hi))
        self.suspects_checked += int(lo.size)
        self.suspects_dup += int(dup.sum())
        return dup

    def close(self) -> None:
        """Release the spill tier's background compactor (see
        HostSpillStore.close) — called whenever an engine replaces its
        store (reset / checkpoint restore)."""
        self.store.close()

    # -- reporting / reconstruction -------------------------------------------

    def stats(self, hot_claims: int) -> dict:
        """The per-tier counters the bench detail and Explorer surface."""
        out = {
            "store": "tiered",
            "hot_fill": round(hot_claims / max(self.size, 1), 4),
            "spilled_states": len(self.store),
            "spill_events": self.spill_events,
            "suspects_checked": self.suspects_checked,
            "suspects_dup": self.suspects_dup,
        }
        if self.evict_bytes_unfiltered:
            # Device-side pre-filter effectiveness: bytes that actually
            # crossed PCIe vs what full-window transfers would have moved.
            out["evict_bytes_pcie"] = self.evict_bytes_pcie
            out["evict_bytes_unfiltered"] = self.evict_bytes_unfiltered
        return out

    def parent_map(self) -> dict:
        return self.store.parent_map()

    # -- checkpoint ------------------------------------------------------------

    def to_checkpoint(self) -> dict:
        """Arrays for the engine checkpoint (the summary is NOT serialized:
        it is a pure function of the spilled fingerprints and is rebuilt on
        load — smaller files, and summary_log2 can even change on resume)."""
        fps, parents = self.store.to_arrays()
        return {"spill_fps": fps, "spill_parents": parents}

    def meta(self) -> dict:
        c = self.config
        return {
            "high_water": c.high_water,
            "low_water": c.resolved_low_water(),
            "summary_log2": c.summary_log2,
            "summary_hashes": c.summary_hashes,
            "spill_events": self.spill_events,
        }

    @classmethod
    def from_checkpoint(
        cls,
        table_size: int,
        meta: dict,
        spill_fps: np.ndarray,
        spill_parents: np.ndarray,
        background: bool = True,
    ) -> "TieredStore":
        cfg = TieredConfig(
            high_water=meta["high_water"],
            low_water=meta["low_water"],
            summary_log2=meta["summary_log2"],
            summary_hashes=meta["summary_hashes"],
        )
        ts = cls(table_size, cfg, background=background)
        fps = np.asarray(spill_fps, dtype=np.uint64)
        ts.store.close()  # replaced wholesale below
        ts.store = HostSpillStore.from_arrays(
            fps, spill_parents, background=background
        )
        ts.spill_events = int(meta.get("spill_events", 0))
        host_insert(
            ts.summary_np,
            (fps & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            (fps >> np.uint64(32)).astype(np.uint32),
            cfg.summary_log2,
            cfg.summary_hashes,
        )
        return ts
