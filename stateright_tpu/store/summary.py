"""Bloom-style device summary of the spilled fingerprint set.

The hot path stays on device: after the visited-table insert claims a slot
for a first-seen key, the engine tests the claim against this summary in the
same jitted step. A miss proves the key was never spilled (Bloom filters
have no false negatives), so the state is new and is enqueued with zero host
involvement — the overwhelmingly common case. A hit makes the key a SUSPECT:
possibly a duplicate of a spilled state, resolved exactly by the host
against `HostSpillStore` between dispatches.

The bit array is uint32 words. Only the host ever SETS bits (at eviction,
`host_insert` — numpy, outside any trace); the device only reads
(`maybe_contains`, k gathers + bit tests), so there is no scatter-OR race to
lower and the engines can carry the words through a `lax.while_loop`
untouched.

Hashing: Kirsch-Mitzenmacher double hashing — two murmur-style mixes of the
(lo, hi) fingerprint pair give h1, h2; probe i tests bit (h1 + i*h2) mod m.
The arithmetic is written against plain uint32 array ops so the SAME helper
serves numpy (host insert, tests) and jax.numpy (device probe).
"""

from __future__ import annotations

import numpy as np

# murmur3 fmix32 constants (public domain) — numpy scalars, not jnp, so
# importing this module never initializes a device backend.
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)
_C1 = np.uint32(0x9E3779B9)
_C2 = np.uint32(0x7F4A7C15)

DEFAULT_HASHES = 4


def summary_words(summary_log2: int) -> int:
    """Word count of a 2^summary_log2-bit summary (>= 1 word)."""
    if summary_log2 < 5:
        raise ValueError("summary_log2 must be >= 5 (one uint32 word)")
    return 1 << (summary_log2 - 5)


def _mix(h):
    """fmix32 over uint32 arrays; works for numpy and jax.numpy inputs."""
    h = (h ^ (h >> 16)) * _M1
    h = (h ^ (h >> 13)) * _M2
    return h ^ (h >> 16)


def _h1h2(lo, hi):
    """The double-hash pair. h2 is forced odd so the probe stride is
    coprime with the power-of-two bit count (all k probes distinct)."""
    h1 = _mix(lo ^ _C1)
    h2 = _mix(hi ^ _C2) | np.uint32(1)
    return h1, h2


def maybe_contains(bits, lo, hi, summary_log2: int, hashes: int = DEFAULT_HASHES):
    """bool[B]: True iff every probe bit is set (possible member); False is
    a PROOF of absence. Traceable (pure gathers + bit ops) — `bits` may be a
    device array inside a jitted step — and equally valid on numpy inputs."""
    mask = np.uint32((1 << summary_log2) - 1)
    h1, h2 = _h1h2(lo, hi)
    hit = None
    for i in range(hashes):
        pos = (h1 + np.uint32(i) * h2) & mask
        word = bits[(pos >> 5).astype(np.int32)]
        bit = ((word >> (pos & np.uint32(31))) & np.uint32(1)).astype(bool)
        hit = bit if hit is None else (hit & bit)
    return hit


def host_insert(
    bits: np.ndarray, lo: np.ndarray, hi: np.ndarray,
    summary_log2: int, hashes: int = DEFAULT_HASHES,
) -> None:
    """Set the probe bits for a batch of fingerprints IN PLACE (numpy only;
    called at eviction time, never inside a trace)."""
    mask = np.uint32((1 << summary_log2) - 1)
    lo = np.asarray(lo, dtype=np.uint32)
    hi = np.asarray(hi, dtype=np.uint32)
    h1, h2 = _h1h2(lo, hi)
    for i in range(hashes):
        pos = (h1 + np.uint32(i) * h2) & mask
        np.bitwise_or.at(
            bits, (pos >> 5).astype(np.int64), np.uint32(1) << (pos & np.uint32(31))
        )
