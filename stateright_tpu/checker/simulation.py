"""Simulation checker: repeated random root-to-terminal traversals
(ref: src/checker/simulation.rs).

Aims for fast coverage of deep states in models too large to check
exhaustively. Each trace keeps a local visited set for cycle detection; there is
no global dedup, so `unique_state_count` equals `state_count`
(ref: src/checker/simulation.rs:413-417).

The reference FIXMEs its nonreproducible StdRng
(ref: src/checker/simulation.rs:47,154); here choosers use Python's
`random.Random(seed)`, which IS reproducible across runs and versions of this
framework, and the vmapped device analogue (stateright_tpu.tensor.simulation)
uses `jax.random` with explicit keys.

`spawn_simulation(device=True)` / `spawn_tpu(mode="simulation")` run the
device engine behind this same `Checker` interface
(`DeviceSimulationChecker` below): thousands of continuously-rebatched
walks per dispatch, an optional shared visited table (`dedup="shared"`),
and the builder's finish_when / target_state_count / target_max_depth /
timeout config mapped onto the rounds loop.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from ..core.fingerprint import Fingerprint, fingerprint
from ..core.model import Expectation
from ..core.path import Path
from ._search import evaluate_properties, record_terminal_ebits
from .base import Checker


class Chooser:
    """Chooses transitions during a simulation run; created per thread
    (ref: src/checker/simulation.rs:22-39)."""

    def new_state(self, seed: int):
        raise NotImplementedError

    def choose_initial_state(self, chooser_state, init_states: list) -> int:
        raise NotImplementedError

    def choose_action(self, chooser_state, current_state, actions: list) -> int:
        raise NotImplementedError


class UniformChooser(Chooser):
    """Uniform random choices (ref: src/checker/simulation.rs:41-79)."""

    def new_state(self, seed: int):
        return random.Random(seed)

    def choose_initial_state(self, rng: random.Random, init_states: list) -> int:
        return rng.randrange(len(init_states))

    def choose_action(self, rng: random.Random, current_state, actions: list) -> int:
        return rng.randrange(len(actions))


class SimulationChecker(Checker):
    def __init__(self, options, seed: int, chooser: Chooser):
        super().__init__(options.model)
        model = options.model
        self._lock = threading.Lock()
        self._properties = model.properties()
        self._symmetry = options.symmetry_fn_
        self._visitor = options.visitor_
        self._finish_when = options.finish_when_
        self._target_state_count = options.target_state_count_
        self._target_max_depth = options.target_max_depth_
        self._timeout = options.timeout_
        self._state_count = 0
        self._max_depth = 0
        self._discoveries: dict[str, list[Fingerprint]] = {}
        self._shutdown = False
        self._threads = []
        self._panic = None
        for t in range(options.thread_count_):
            th = threading.Thread(
                target=self._worker,
                args=(seed + t, chooser),
                name=f"checker-{t}",
                daemon=True,
            )
            th.start()
            self._threads.append(th)

    def _worker(self, seed: int, chooser: Chooser) -> None:
        """Per-thread loop: run traces with fresh seeds until a finish condition
        (ref: src/checker/simulation.rs:151-196)."""
        deadline = (
            time.monotonic() + self._timeout if self._timeout is not None else None
        )
        rng = random.Random(seed)
        try:
            while True:
                if self._shutdown:
                    return
                if deadline is not None and time.monotonic() >= deadline:
                    return
                self._check_trace_from_initial(seed, chooser)
                with self._lock:
                    discovered = set(self._discoveries)
                if self._finish_when.matches(self._properties, discovered):
                    return
                if (
                    self._target_state_count is not None
                    and self._target_state_count <= self._state_count
                ):
                    return
                seed = rng.getrandbits(63)
        except BaseException as e:  # noqa: BLE001
            with self._lock:
                if self._panic is None:
                    self._panic = e
        finally:
            self._shutdown = True

    def _check_trace_from_initial(self, seed: int, chooser: Chooser) -> None:
        """One random walk from an initial state to a terminal/loop/boundary
        (ref: src/checker/simulation.rs:213-397)."""
        model = self._model
        properties = self._properties
        chooser_state = chooser.new_state(seed)

        init_states = model.init_states()
        state = init_states[chooser.choose_initial_state(chooser_state, init_states)]

        fingerprint_path: list[Fingerprint] = []
        generated: set[Fingerprint] = set()
        ebits = frozenset(
            i
            for i, p in enumerate(properties)
            if p.expectation == Expectation.EVENTUALLY
        )

        while True:
            if len(fingerprint_path) > self._max_depth:
                with self._lock:
                    self._max_depth = max(self._max_depth, len(fingerprint_path))
            if (
                self._target_max_depth is not None
                and len(fingerprint_path) >= self._target_max_depth
            ):
                # Not known to be terminal: skip the eventually check entirely
                # (the reference `return`s rather than `break`s here,
                # ref: src/checker/simulation.rs:264-274).
                return

            if not model.within_boundary(state):
                break

            fp = fingerprint(state)
            fingerprint_path.append(fp)
            canonical_fp = (
                fingerprint(self._symmetry(state))
                if self._symmetry is not None
                else fp
            )
            if canonical_fp in generated:
                break  # found a loop
            generated.add(canonical_fp)

            with self._lock:
                self._state_count += 1

            if self._visitor is not None and self._visitor.should_visit():
                self._visitor.visit(
                    model, Path.from_fingerprints(model, fingerprint_path)
                )

            is_awaiting_discoveries, ebits = evaluate_properties(
                model,
                properties,
                state,
                self._discoveries,
                self._lock,
                list(fingerprint_path),
                ebits,
            )
            if not is_awaiting_discoveries:
                break

            actions: list = []
            model.actions(state, actions)
            advanced = False
            while actions:
                index = chooser.choose_action(chooser_state, state, actions)
                action = actions[index]
                actions[index] = actions[-1]
                actions.pop()  # swap_remove
                next_state = model.next_state(state, action)
                if next_state is not None:
                    state = next_state
                    advanced = True
                    break
            if not advanced:
                break  # no actions: genuine terminal

        # Check the eventually properties at the end of the walk; the reference
        # reaches this on every break — loop, boundary, or terminal
        # (ref: src/checker/simulation.rs:390-397).
        record_terminal_ebits(
            properties, ebits, self._discoveries, self._lock, list(fingerprint_path)
        )

    # -- Checker interface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._state_count  # no global dedup

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> dict[str, Path]:
        with self._lock:
            items = list(self._discoveries.items())
        return {
            name: Path.from_fingerprints(self._model, fps)
            for name, fps in items
            if fps
        }

    def join(self) -> "SimulationChecker":
        for th in self._threads:
            th.join()
        if self._panic is not None:
            raise self._panic
        return self

    def is_done(self) -> bool:
        return all(not th.is_alive() for th in self._threads)


class DeviceSimulationChecker(Checker):
    """The device random-walk engine (stateright_tpu/tensor/simulation.py)
    behind the standard `Checker` handle — the fourth checker mode's
    plug-in boundary, exactly like `TpuChecker` for the frontier search.

    The builder config maps onto the rounds loop the way the host
    checker's per-thread trace loop consumes it: `finish_when` stops the
    rounds once matched, `target_state_count` bounds total generated
    states, `target_max_depth` caps the walk depth, and `timeout` bounds
    wall time between rounds. With no properties and no target/timeout the
    checker runs exactly one round (the host checker would walk forever)."""

    def __init__(self, options, seed: int = 0, **kwargs):
        from ..tensor.model import TensorModel
        from ..tensor.simulation import DeviceSimulation

        model = options.model
        if not isinstance(model, TensorModel):
            raise TypeError(
                "spawn_simulation(device=True) requires a stateright_tpu."
                f"tensor.TensorModel; got {type(model).__name__}. Host "
                "Models run on the thread-pool SimulationChecker; tensor "
                "encodings of the bundled workloads live in "
                "stateright_tpu.tensor.models."
            )
        if options.visitor_ is not None:
            raise NotImplementedError(
                "visitors are not supported on the device simulation "
                "engine; use spawn_simulation() (host) or spawn_tpu()"
            )
        if options.symmetry_fn_ is not None:
            raise NotImplementedError(
                "the builder's symmetry_fn is a host-level callable; device "
                "symmetry reduction is the TensorModel.representative "
                "kernel (see spawn_tpu)"
            )
        super().__init__(model)
        if options.target_max_depth_ is not None:
            kwargs.setdefault("max_depth", options.target_max_depth_)
        self._sim = DeviceSimulation(model, seed=seed, **kwargs)
        self._options = options
        self._result = None
        self._discovery_paths = None
        self._panic: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        options = self._options
        finish = options.finish_when_
        target = options.target_state_count_
        deadline = (
            time.monotonic() + options.timeout_
            if options.timeout_ is not None
            else None
        )
        props = self._sim.props
        try:
            while True:
                r = self._sim.run(finish_when=finish)
                self._result = r
                if finish.matches(props, set(r.discoveries)):
                    return
                if target is not None and r.state_count >= target:
                    return
                if deadline is not None and time.monotonic() >= deadline:
                    return
                if not props and target is None and deadline is None:
                    return  # nothing to converge on: one round
        except BaseException as e:  # noqa: BLE001 — surfaced by join()
            self._panic = e

    # -- Checker interface -----------------------------------------------------

    def state_count(self) -> int:
        r = self._result
        return r.state_count if r is not None else 0

    def unique_state_count(self) -> int:
        r = self._result
        return r.unique_state_count if r is not None else 0

    def max_depth(self) -> int:
        r = self._result
        return r.max_depth if r is not None else 0

    def table_fill(self) -> Optional[float]:
        """Shared-table coverage fill (None for per-walk dedup, which has
        no global table to fill)."""
        if self._sim.table is None:
            return None
        return min(
            self.unique_state_count() / (1 << self._sim.table_log2), 1.0
        )

    def telemetry_summary(self) -> Optional[dict]:
        """The engine's walk-plane digest (obs/schema.py TELEMETRY_KEYS;
        None with telemetry off) — surfaced like TpuChecker's."""
        return self._sim.telemetry_summary()

    def discoveries(self) -> dict[str, Path]:
        if self._result is None:
            return {}
        if self._discovery_paths is not None:
            return dict(self._discovery_paths)
        paths = {
            name: self._sim.discovery_path(name)
            for name in self._result.discoveries
        }
        if self.is_done():
            # Cache only the final set: a mid-run poll sees a snapshot,
            # but later rounds may still add discoveries.
            self._discovery_paths = paths
        return paths

    def join(self) -> "DeviceSimulationChecker":
        self._thread.join()
        if self._panic is not None:
            raise self._panic
        return self

    def is_done(self) -> bool:
        return not self._thread.is_alive()
