"""Host depth-first checker (ref: src/checker/dfs.rs).

Uses dramatically less memory than BFS (visited set of fingerprints only; jobs
carry their full fingerprint path instead of relying on parent pointers) at the
cost of longer discovery paths. This is the only checker supporting symmetry
reduction: on insert, the fingerprint of the *representative* is recorded, but
the search continues from the original state/fingerprint so the collected path
remains extendable — the subtle bug-fix the reference documents at
src/checker/dfs.rs:315-318.
"""

from __future__ import annotations

import threading
from collections import deque

from ..core.fingerprint import Fingerprint, fingerprint
from ..core.model import Expectation
from ..core.path import Path
from itertools import islice

from ._search import (
    WorkerLoopMixin,
    evaluate_properties,
    plane_activity,
    prefetch_block_verdicts,
    state_carries_tester,
    record_terminal_ebits,
)
from .base import Checker
from .job_market import JobBroker


class DfsChecker(WorkerLoopMixin, Checker):
    BLOCK_SIZE = 1500  # ref: src/checker/dfs.rs:133

    def __init__(self, options):
        super().__init__(options.model)
        model = options.model
        self._lock = threading.Lock()
        self._properties = model.properties()
        self._symmetry = options.symmetry_fn_
        self._visitor = options.visitor_
        self._finish_when = options.finish_when_
        self._target_state_count = options.target_state_count_
        self._target_max_depth = options.target_max_depth_

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._max_depth = 0
        self._generated: set[Fingerprint] = set()
        for s in init_states:
            if self._symmetry is not None:
                self._generated.add(fingerprint(self._symmetry(s)))
            else:
                self._generated.add(fingerprint(s))
        # name -> full fingerprint path (ref: src/checker/dfs.rs:29)
        self._discoveries: dict[str, list[Fingerprint]] = {}

        ebits = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        pending = deque()
        for s in init_states:
            pending.append((s, [fingerprint(s)], ebits, 1))

        self._broker: JobBroker = JobBroker.new(options.thread_count_, options.close_at)
        self._broker.push(pending)
        self._threads = []
        for t in range(options.thread_count_):
            th = threading.Thread(target=self._worker, name=f"checker-{t}", daemon=True)
            th.start()
            self._threads.append(th)

    def _check_block(self, pending: deque, max_count: int) -> None:
        """The hot loop (ref: src/checker/dfs.rs:182-358)."""
        model = self._model
        properties = self._properties
        symmetry = self._symmetry
        # Chunk-boundary verdict prefetch (dedup-first semantics),
        # feedback-gated exactly like bfs.py: a block whose property loop
        # never consults the plane disables further prefetching.
        probe_mark = None
        if getattr(self, "_plane_prefetch", True) and pending:
            if not state_carries_tester(pending[-1][0]):
                # Tester-less model: prefetching can never pay off — disable
                # before ever materializing a block copy.
                self._plane_prefetch = False
            else:
                prefetched = prefetch_block_verdicts(
                    list(islice(reversed(pending), max_count))
                )
                if prefetched:
                    probe_mark = plane_activity()
        while max_count > 0 and pending:
            max_count -= 1
            state, fingerprints, ebits, depth = pending.pop()

            if depth > self._max_depth:
                with self._lock:
                    self._max_depth = max(self._max_depth, depth)
            if self._target_max_depth is not None and depth >= self._target_max_depth:
                continue

            if self._visitor is not None and self._visitor.should_visit():
                self._visitor.visit(
                    model, Path.from_fingerprints(model, fingerprints)
                )

            is_awaiting_discoveries, ebits = evaluate_properties(
                model,
                properties,
                state,
                self._discoveries,
                self._lock,
                list(fingerprints),
                ebits,
            )
            if not is_awaiting_discoveries:
                return

            is_terminal = True
            actions: list = []
            model.actions(state, actions)
            for action in actions:
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                with self._lock:
                    self._state_count += 1
                if symmetry is not None:
                    # Dedup on the canonical member, continue with the original
                    # (ref: src/checker/dfs.rs:309-318).
                    rep_fp = fingerprint(symmetry(next_state))
                    with self._lock:
                        if rep_fp in self._generated:
                            is_terminal = False
                            continue
                        self._generated.add(rep_fp)
                    next_fp = fingerprint(next_state)
                else:
                    next_fp = fingerprint(next_state)
                    with self._lock:
                        if next_fp in self._generated:
                            is_terminal = False
                            continue
                        self._generated.add(next_fp)
                is_terminal = False
                pending.append(
                    (next_state, fingerprints + [next_fp], ebits, depth + 1)
                )
            if is_terminal:
                record_terminal_ebits(
                    properties, ebits, self._discoveries, self._lock, list(fingerprints)
                )
        if probe_mark is not None and plane_activity() == probe_mark:
            self._plane_prefetch = False  # block went unconsumed: stop

    # -- Checker interface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> dict[str, Path]:
        with self._lock:
            items = list(self._discoveries.items())
        return {
            name: Path.from_fingerprints(self._model, fps) for name, fps in items
        }

    def join(self) -> "DfsChecker":
        for th in self._threads:
            th.join()
        if self._broker.market.panic is not None:
            raise self._broker.market.panic
        return self

    def is_done(self) -> bool:
        return (
            self._broker.is_closed()
            or len(self._discoveries) == len(self._properties)
            or all(not th.is_alive() for th in self._threads)
        )
