"""Work-sharing market for host checker threads (ref: src/job_market.rs).

The reference coordinates checker threads through a mutex-protected job market:
`pop` blocks until work arrives or every thread goes idle with no jobs left
(global quiescence closes the market); `split_and_push` rebalances a busy
thread's local queue to idle peers; and any thread exiting — normal early
finish or panic — closes the market on the way out (the reference does this in
`Drop`, ref: src/job_market.rs:29-41), which is how "one thread found all
discoveries" propagates to the others.

The host checkers keep this protocol for semantics parity (Python threads share
the GIL, so it is scheduler logic, not CPU scaling — the TPU path replaces it
with collectives, see stateright_tpu.tensor).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Generic, Optional, TypeVar

Job = TypeVar("Job")


class _Market(Generic[Job]):
    """Shared state (ref: src/job_market.rs:43-52)."""

    def __init__(self, thread_count: int, close_at: Optional[float]):
        self.cond = threading.Condition()
        self.open = True
        self.thread_count = thread_count
        self.open_count = thread_count  # threads currently working
        self.job_batches: list[Deque[Job]] = []
        self.close_at = close_at  # monotonic deadline, None = no timeout
        self.panic: Optional[BaseException] = None


class JobBroker(Generic[Job]):
    """Per-thread handle to the market (ref: src/job_market.rs:13-41)."""

    def __init__(self, market: _Market[Job]):
        self.market = market

    @staticmethod
    def new(thread_count: int, close_at: Optional[float]) -> "JobBroker[Job]":
        return JobBroker(_Market(thread_count, close_at))

    def push(self, jobs: Deque[Job]) -> None:
        """Publish a batch (ref: src/job_market.rs:133-145)."""
        m = self.market
        with m.cond:
            if not m.open or not jobs:
                return
            m.job_batches.append(jobs)
            m.cond.notify()

    def pop(self) -> Deque[Job]:
        """Blocks until jobs are available or the market closes; an empty deque
        means "shut down" (ref: src/job_market.rs:95-130)."""
        m = self.market
        with m.cond:
            while True:
                if m.close_at is not None and time.monotonic() >= m.close_at:
                    m.open = False
                    m.job_batches.clear()
                    m.cond.notify_all()
                if not m.open and not m.job_batches:
                    m.open_count = max(0, m.open_count - 1)
                    m.cond.notify_all()
                    return deque()
                if m.job_batches:
                    return m.job_batches.pop()
                m.open_count -= 1
                if m.open_count == 0:
                    # Last running thread and no jobs: global quiescence.
                    m.open = False
                    m.cond.notify_all()
                    return deque()
                timeout = 0.5
                if m.close_at is not None:
                    timeout = min(timeout, max(0.0, m.close_at - time.monotonic()))
                m.cond.wait(timeout=timeout)
                m.open_count += 1

    def split_and_push(self, jobs: Deque[Job]) -> None:
        """Splits the local queue into one piece per idle thread and publishes
        them; on a closed market the local queue is discarded so the caller
        stops promptly (ref: src/job_market.rs:149-176)."""
        m = self.market
        with m.cond:
            if not m.open:
                jobs.clear()
                return
            pieces = 1 + min(max(0, m.thread_count - m.open_count), len(jobs))
            size = len(jobs) // pieces
            for _ in range(pieces - 1):
                if size == 0:
                    break
                piece: Deque[Job] = deque()
                for _ in range(size):
                    piece.append(jobs.pop())
                m.job_batches.append(piece)
                m.cond.notify()

    def thread_exited(self, panic: Optional[BaseException] = None) -> None:
        """A checker thread is exiting: close the market and wake everyone,
        mirroring the reference's Drop impl (ref: src/job_market.rs:29-41)."""
        m = self.market
        with m.cond:
            m.open = False
            m.job_batches.clear()
            m.open_count = max(0, m.open_count - 1)
            if panic is not None and m.panic is None:
                m.panic = panic
            m.cond.notify_all()

    def deadline_passed(self) -> bool:
        """Whether the timeout deadline has passed; closes the market if so.
        Workers poll this between blocks — the reference instead runs a
        dedicated timeout thread that closes the market
        (ref: src/job_market.rs:69-86)."""
        m = self.market
        if m.close_at is None:
            return False
        if time.monotonic() < m.close_at:
            return False
        with m.cond:
            m.open = False
            m.job_batches.clear()
            m.cond.notify_all()
        return True

    def is_closed(self) -> bool:
        """ref: src/job_market.rs:179-183"""
        m = self.market
        with m.cond:
            return not m.open and not m.job_batches and m.open_count == 0
