"""Checker runtimes: BFS, DFS, simulation, on-demand, and the TPU frontier checker."""
