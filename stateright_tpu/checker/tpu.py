"""The TPU frontier checker behind the standard `Checker` interface — the
plug-in boundary BASELINE.json requires: `TensorModel.checker().spawn_tpu()`
gives the same handle API (counts, discoveries, join, report, assertions) as
the host checkers, with the search executed as batched device kernels.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..core.path import Path
from .base import Checker


class TpuChecker(Checker):
    def __init__(
        self,
        options,
        batch_size: int = 1024,
        table_log2: int = 20,
        resident: bool = None,
        trace_out: Optional[str] = None,
        **engine_kwargs,
    ):
        # engine_kwargs pass through to the underlying engine —
        # ResidentSearch options like table_layout ("split"/"kv"),
        # insert_variant (knobs.INSERT_VARIANTS: "sort"/"phased"/"capped"/
        # "capped-phased"/"pallas" — the last is the partitioned-VMEM
        # Pallas kernel, interpret mode off-TPU),
        # append ("scatter"/"dus"), queue_log2, donate_chunks, the
        # tiered-store knobs (store="tiered", high_water, low_water,
        # summary_log2 — stateright_tpu/store/), and the telemetry knobs
        # (telemetry=..., telemetry_log2=... — stateright_tpu/obs/) — so
        # builder-API users can reach the same design knobs the tuner
        # races. With resident=False the host-orchestrated engine accepts
        # insert_variant, the tiered-store knobs, and telemetry (it races
        # the same visited-set designs). `trace_out=<path>` records host
        # phases as Chrome trace-event JSON, saved when the search thread
        # finishes (load it in Perfetto; see obs/trace.py).
        from ..obs import Tracer
        from ..tensor.frontier import FrontierSearch
        from ..tensor.model import TensorModel
        from ..tensor.resident import ResidentSearch

        model = options.model
        if not isinstance(model, TensorModel):
            raise TypeError(
                "spawn_tpu() requires a stateright_tpu.tensor.TensorModel; "
                f"got {type(model).__name__}. Host Models run on spawn_bfs/"
                "spawn_dfs; tensor encodings of the bundled workloads live in "
                "stateright_tpu.tensor.models."
            )
        if options.symmetry_fn_ is not None:
            raise NotImplementedError(
                "the builder's symmetry_fn is a host-level callable and "
                "cannot run inside a device kernel; device symmetry "
                "reduction is expressed as the TensorModel.representative "
                "canonicalization kernel instead (see tensor/symmetry.py), "
                "which every device engine honors automatically"
            )
        self._recorder = None
        if options.visitor_ is not None:
            # Visitors run POST-SEARCH over the retained carry: a
            # StateRecorder gets the batched queue dump (every evaluated
            # state, one transfer — ref: src/checker/visitor.rs:75-111);
            # any other CheckerVisitor gets a full parent-pointer Path per
            # evaluated state, rebuilt incrementally in BFS queue order
            # (parents always precede children, so each path is its
            # parent's path plus one replayed step — batched expands, one
            # device call per parent chunk). Path building costs
            # O(states x depth) host memory/time: it serves the
            # reference's visitor use case (test-scale assertions), not
            # flagship-scale spaces. The reference calls visitors DURING
            # the search; here results are identical for recorders since
            # the search always runs to its finish policy first.
            if resident is False:
                raise NotImplementedError(
                    "visitors on spawn_tpu require the resident engine "
                    "(the default); drop resident=False"
                )
            if engine_kwargs.get("store") == "tiered":
                raise NotImplementedError(
                    "visitors on spawn_tpu require the device store (the "
                    "tiered store compacts the frontier queue the visitor "
                    "dump reads); drop store='tiered'"
                )
            self._recorder = options.visitor_
        super().__init__(model)
        # The resident engine runs the whole search in one device dispatch —
        # the default. A timeout makes it run in chunked dispatches (the
        # wall clock is polled between chunks), which also feeds the live
        # counters; pass resident=False for the host-orchestrated engine's
        # finer-grained (per-device-step) progress instead.
        if resident is None:
            resident = True
        if not resident:
            unsupported = set(engine_kwargs) - {
                "insert_variant", "store", "high_water", "low_water",
                "summary_log2", "telemetry", "telemetry_log2",
            }
            if unsupported:
                raise ValueError(
                    f"engine options {sorted(unsupported)} require the "
                    "resident engine (drop resident=False)"
                )
        self._trace_out = trace_out
        if trace_out is not None:
            engine_kwargs["tracer"] = Tracer(annotate=True)
        self._search = (
            ResidentSearch(model, batch_size, table_log2, **engine_kwargs)
            if resident
            else FrontierSearch(model, batch_size, table_log2, **engine_kwargs)
        )
        self._options = options
        self._result = None
        self._discovery_paths = None
        self._live = {"states": 0, "unique": 0, "depth": 0}
        self._panic: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        def progress(states, unique, depth):
            self._live["states"] = states
            self._live["unique"] = unique
            self._live["depth"] = depth

        from ..tensor.resident import ResidentSearch

        kwargs = dict(
            finish_when=self._options.finish_when_,
            target_state_count=self._options.target_state_count_,
            target_max_depth=self._options.target_max_depth_,
            timeout=self._options.timeout_,
        )
        if (
            self._options.timeout_ is not None
            or not isinstance(self._search, ResidentSearch)
        ):
            # Chunked/host-orchestrated runs surface live counters; a
            # single-dispatch resident run has no host involvement to report
            # from (forcing it chunked just for counters would cost perf).
            kwargs["progress"] = progress
        if (
            self._recorder is not None
            and isinstance(self._search, ResidentSearch)
            and self._options.timeout_ is None
        ):
            # dump_states() needs the retained carry of a chunked run. With a
            # timeout, _resolve_chunking already picks the 64-step polling
            # budget — overriding it here would defeat the wall clock.
            kwargs.setdefault("budget", 1 << 20)
        try:
            # Chaos-plane boundary: the spawn's search thread itself (the
            # engines add their own per-dispatch points; this one exercises
            # the join()/panic surface — faults/plan.py).
            from ..faults.plan import maybe_fault

            maybe_fault(
                "checker.run", engine=type(self._search).__name__
            )
            with self._search._tracer.span("search.run", cat="checker"):
                self._result = self._search.run(**kwargs)
            if self._recorder is not None:
                from ..core.visitor import StateRecorder

                if isinstance(self._recorder, StateRecorder):
                    from ..core.path import Path as _Path

                    # evaluated_only: rows the search actually popped — on
                    # an early exit the queue tail also holds
                    # never-evaluated frontier rows, which the reference's
                    # visitor never sees.
                    for s in self._search.dump_states(evaluated_only=True):
                        self._recorder.visit(self._model, _Path([(s, None)]))
                else:
                    self._visit_paths()
        except BaseException as e:  # noqa: BLE001 — surfaced by join()
            self._panic = e
        finally:
            if self._trace_out is not None:
                try:
                    self._search._tracer.save(self._trace_out)
                except OSError:
                    pass  # tracing must never fail a finished search

    def _visit_paths(self) -> None:
        """Call the visitor with a full Path for every evaluated state.

        Paths rebuild incrementally in queue order: a child's path is its
        parent's path plus the one step that produced it, found by
        expanding each parent once (batched over chunks of unique parents)
        and matching child fingerprints against the successor table."""
        import numpy as np

        from ..core.path import Path as _Path
        from ..tensor.fingerprint import pack_fp
        from ..tensor.frontier import state_fingerprint

        import jax.numpy as jnp

        search = self._search
        c = search._carry
        if c is None:
            return  # vacuous-finish early exit: nothing was evaluated
        head = int(c.head)
        if head == 0:
            return
        rows = np.asarray(c.q_states[:head])
        fps = pack_fp(np.asarray(c.q_lo[:head]), np.asarray(c.q_hi[:head]))
        parent_of = search.build_parent_map()  # layout-aware, cached
        idx_of = {int(f): i for i, f in enumerate(fps)}
        model = self._model

        # One batched expand per chunk of unique parents; per parent, map
        # successor fingerprint -> action slot.
        action_cache: dict[int, dict[int, int]] = {}

        def succ_actions(parent_idxs: list[int]) -> None:
            batch = jnp.asarray(rows[parent_idxs])
            succs, valid = model.expand(batch)
            B, A = valid.shape
            flat = succs.reshape(B * A, model.lanes)
            # Boundary-mask exactly like the search itself
            # (frontier.expand_insert): a boundary-excluded action is not a
            # transition and must never label a path step.
            validn = (
                np.asarray(valid.reshape(-1) & model.within_boundary(flat))
                .reshape(B, A)
            )
            slo, shi = state_fingerprint(model, flat)
            sfps = pack_fp(np.asarray(slo), np.asarray(shi)).reshape(B, A)
            for j, pi in enumerate(parent_idxs):
                # First matching slot wins (reversed dict build keeps the
                # LOWEST action index on fingerprint ties), matching the
                # insert's first-writer semantics closely enough for replay.
                action_cache[pi] = {
                    int(sfps[j, a]): a
                    for a in reversed(range(A))
                    if validn[j, a]
                }

        CHUNK = 512
        need: list[int] = []
        seen_parents = set()
        for i in range(head):
            pfp = parent_of.get(int(fps[i]), 0)
            pi = idx_of.get(pfp)
            if pi is not None and pi not in seen_parents:
                seen_parents.add(pi)
                need.append(pi)
        for k in range(0, len(need), CHUNK):
            succ_actions(need[k : k + CHUNK])

        paths: list[Optional[list]] = [None] * head
        for i in range(head):
            state = model.decode(rows[i])
            pfp = parent_of.get(int(fps[i]), 0)
            pi = idx_of.get(pfp)
            if pi is None or paths[pi] is None:
                pairs = [(state, None)]
            else:
                a = action_cache[pi].get(int(fps[i]))
                label = (
                    model.action_label(rows[pi], a) if a is not None else None
                )
                parent_pairs = paths[pi]
                pairs = (
                    parent_pairs[:-1]
                    + [(parent_pairs[-1][0], label), (state, None)]
                )
            paths[i] = pairs
            if self._recorder.should_visit():
                # The visitor API's rate-limit hook: honored AFTER the path
                # list is extended (cheap) but gating the Path build + call,
                # like the host checkers (e.g. checker/bfs.py).
                self._recorder.visit(model, _Path(list(pairs)))

    # -- Checker interface -----------------------------------------------------

    def state_count(self) -> int:
        r = self._result
        return r.state_count if r is not None else self._live["states"]

    def unique_state_count(self) -> int:
        r = self._result
        return r.unique_state_count if r is not None else self._live["unique"]

    def max_depth(self) -> int:
        r = self._result
        return r.max_depth if r is not None else self._live["depth"]

    def store_stats(self) -> Optional[dict]:
        """Per-tier occupancy of the engine's state store (None unless the
        engine runs store="tiered") — surfaced in the Explorer `/.status`."""
        stats = getattr(self._search, "store_stats", None)
        return stats() if stats is not None else None

    def telemetry_summary(self) -> Optional[dict]:
        """The engine's step-telemetry digest (obs/ring.py; None with
        telemetry off) — surfaced in the Explorer `/.status`/`/metrics`."""
        t = getattr(self._search, "telemetry_summary", None)
        return t() if t is not None else None

    def table_fill(self) -> Optional[float]:
        """Visited-table fill for the WriteReporter `fill=` field: the
        tiered store's exact hot_fill when present, else live uniques over
        table slots (exact for the device store — claims == uniques)."""
        stats = self.store_stats()
        if stats and "hot_fill" in stats:
            return stats["hot_fill"]
        log2 = getattr(self._search, "table_log2", None)
        if log2 is None:
            log2 = self._search.table.log2_size
        return min(self.unique_state_count() / (1 << log2), 1.0)

    def drift_ratio(self) -> Optional[float]:
        """Measured/predicted ratio of the engine's live calibration
        comparator (obs/calib.py) for the WriteReporter `drift=` field;
        None until its first chunk closes (or with calibration off)."""
        calib = getattr(self._search, "_calib", None)
        return calib.drift_ratio() if calib is not None else None

    def discoveries(self) -> dict[str, Path]:
        if self._result is None:
            return {}
        if self._discovery_paths is None:
            # Reconstruction dumps the device table; results are immutable
            # once the search thread finishes, so build the paths once.
            self._discovery_paths = {
                name: self._search.reconstruct_path(fp)
                for name, fp in self._result.discoveries.items()
            }
        return dict(self._discovery_paths)

    def join(self) -> "TpuChecker":
        self._thread.join()
        if self._panic is not None:
            raise self._panic
        return self

    def is_done(self) -> bool:
        return not self._thread.is_alive()

    def assert_discovery(self, name, actions) -> None:
        """Panics unless `actions` (a list of the model's `action_label`
        values) also constitutes a valid discovery, validated by re-executing
        the tensor model (ref: src/checker.rs:521-577)."""
        import numpy as np

        from ..core.model import Expectation

        found = self.assert_any_discovery(name)
        model = self._model
        prop = model.property_by_name(name)
        additional_info: list[str] = []

        def cond(row) -> bool:
            import jax.numpy as jnp

            return bool(
                np.asarray(prop.condition(model, jnp.asarray(row[None])))[0]
            )

        for init_row in np.asarray(model.init_states()):
            states = self._replay(init_row, actions)
            if states is None:
                continue
            if prop.expectation == Expectation.ALWAYS:
                if not cond(states[-1]):
                    return
            elif prop.expectation == Expectation.EVENTUALLY:
                liveness_satisfied = any(cond(s) for s in states)
                terminal = self._is_terminal(states[-1])
                if not liveness_satisfied and terminal:
                    return
                if liveness_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property"
                    )
                if not terminal:
                    additional_info.append(
                        "incorrect counterexample is nonterminal"
                    )
            else:  # SOMETIMES
                if cond(states[-1]):
                    return
        extra = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{extra}, but a valid one was '
            f"found. found={found.actions()!r}"
        )

    def _valid_successors(self, row):
        """(successors, mask) with boundary-excluded successors masked out —
        the engines' notion of a transition (frontier.expand_insert)."""
        import jax.numpy as jnp
        import numpy as np

        model = self._model
        succs, valid = model.expand(jnp.asarray(np.asarray(row)[None]))
        in_bounds = model.within_boundary(succs[0])
        return np.asarray(succs)[0], np.asarray(valid)[0] & np.asarray(
            in_bounds
        )

    def _replay(self, init_row, actions):
        """Re-execute the tensor model along a list of action labels;
        returns the state rows visited, or None if a label has no valid
        matching action somewhere along the way."""
        import numpy as np

        model = self._model
        cur = np.asarray(init_row, dtype=np.uint32)
        states = [cur]
        for action in actions:
            succs, valid = self._valid_successors(cur)
            nxt = None
            for a in range(model.max_actions):
                if valid[a] and model.action_label(cur, a) == action:
                    nxt = succs[a]
                    break
            if nxt is None:
                return None
            cur = nxt
            states.append(cur)
        return states

    def _is_terminal(self, row) -> bool:
        _succs, valid = self._valid_successors(row)
        return not bool(valid.any())
