"""Checker configuration builder (ref: src/checker.rs:65-288).

Instantiated via `Model.checker()`; fluent config then one of the `spawn_*`
methods. Beyond the reference's strategies (bfs/dfs/on_demand/simulation), this
builder adds `spawn_tpu()` — the batched device frontier checker — behind the
same `Checker` interface, the plug-in boundary BASELINE.json requires.

Memory note: consistency-tester properties (linearizability / sequential
consistency) memoize serialization verdicts in bounded process-global caches
(2^15 entries each) that retain tester histories after a run completes; a
long-lived process checking many unrelated models can call
`stateright_tpu.semantics.clear_serialization_caches()` between runs to
release them.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..core.discovery import HasDiscoveries
from ..core.visitor import as_visitor


class CheckerBuilder:
    def __init__(self, model):
        self.model = model
        self.symmetry_fn_: Optional[Callable] = None
        self.target_state_count_: Optional[int] = None
        self.target_max_depth_: Optional[int] = None
        self.thread_count_: int = 1
        self.visitor_ = None
        self.finish_when_: HasDiscoveries = HasDiscoveries.ALL
        self.timeout_: Optional[float] = None
        self.trace_out_: Optional[str] = None

    # -- config (fluent; ref: src/checker.rs:219-287) --------------------------

    def symmetry(self) -> "CheckerBuilder":
        """Enable symmetry reduction via the state's `representative()` method
        (ref: src/checker.rs:222-227)."""
        return self.symmetry_fn(lambda state: state.representative())

    def symmetry_fn(self, representative: Callable) -> "CheckerBuilder":
        self.symmetry_fn_ = representative
        return self

    def finish_when(self, has_discoveries: HasDiscoveries) -> "CheckerBuilder":
        self.finish_when_ = has_discoveries
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        self.target_state_count_ = count if count > 0 else None
        return self

    def target_max_depth(self, depth: int) -> "CheckerBuilder":
        self.target_max_depth_ = depth if depth > 0 else None
        return self

    def threads(self, thread_count: int) -> "CheckerBuilder":
        self.thread_count_ = max(1, thread_count)
        return self

    def visitor(self, visitor) -> "CheckerBuilder":
        self.visitor_ = as_visitor(visitor)
        return self

    def timeout(self, seconds: float) -> "CheckerBuilder":
        self.timeout_ = seconds
        return self

    def trace_out(self, path: str) -> "CheckerBuilder":
        """Record the spawned checker's host phases (dispatch, tiered-store
        servicing, checkpointing) as Chrome trace-event JSON at `path` —
        viewable in Perfetto (stateright_tpu/obs/trace.py). Honored by
        `spawn_tpu`; the host checkers ignore it."""
        self.trace_out_ = path
        return self

    @property
    def close_at(self) -> Optional[float]:
        return None if self.timeout_ is None else time.monotonic() + self.timeout_

    # -- spawn (ref: src/checker.rs:144-217) -----------------------------------

    def spawn_bfs(self):
        from .bfs import BfsChecker

        return BfsChecker(self)

    def spawn_dfs(self):
        from .dfs import DfsChecker

        return DfsChecker(self)

    def spawn_simulation(
        self, seed: int = 0, chooser=None, device: bool = False, **kwargs
    ):
        """Spawn the random-simulation checker (the fourth checker mode,
        ref: src/checker/simulation.rs). `device=False` (default) runs the
        host thread-pool walker over a host `Model`; `device=True` runs the
        vmapped device engine (tensor/simulation.py) over a `TensorModel` —
        thousands of continuously-rebatched walks per dispatch, with
        `kwargs` passing through to `DeviceSimulation` (traces, max_depth,
        dedup="trace"/"shared", table_log2, insert_variant, walks,
        stale_limit, salt, continuous, telemetry)."""
        if device:
            if chooser is not None:
                raise ValueError(
                    "chooser is a host-walker hook; the device engine "
                    "draws from counter-based jax.random streams"
                )
            from .simulation import DeviceSimulationChecker

            return DeviceSimulationChecker(self, seed=seed, **kwargs)
        from .simulation import SimulationChecker, UniformChooser

        if kwargs:
            raise TypeError(
                f"options {sorted(kwargs)} require the device engine "
                "(spawn_simulation(device=True, ...))"
            )
        return SimulationChecker(self, seed, chooser or UniformChooser())

    def spawn_on_demand(self):
        try:
            from .on_demand import OnDemandChecker
        except ImportError as e:
            raise NotImplementedError(
                "the on-demand checker has not landed yet in this build"
            ) from e
        return OnDemandChecker(self)

    def serve(self, address: str = "localhost:3000", block: bool = False):
        """Start the Explorer web service (ref: src/checker.rs:144-151)."""
        try:
            from ..explorer.server import serve
        except ImportError as e:
            raise NotImplementedError(
                "the Explorer web service has not landed yet in this build"
            ) from e
        return serve(self, address, block=block)

    def spawn_tpu(self, mode: str = "search", **kwargs):
        """Spawn a batched device (TPU) checker. The model must be a
        `stateright_tpu.tensor.TensorModel` or provide one via
        `tensor_model()`. `mode` picks the engine (knobs.CHECKER_MODES):
        "search" (default) is the exhaustive frontier checker;
        "simulation" is the device random-walk engine — equivalent to
        `spawn_simulation(device=True, **kwargs)`."""
        from ..knobs import CHECKER_MODES

        if mode not in CHECKER_MODES:  # knob universe: knobs.py
            raise ValueError(
                f"mode must be one of {CHECKER_MODES}, got {mode!r}"
            )
        if mode == "simulation":
            return self.spawn_simulation(device=True, **kwargs)
        try:
            from .tpu import TpuChecker
        except ImportError as e:
            raise NotImplementedError(
                "the TPU frontier checker has not landed yet in this build"
            ) from e
        if self.trace_out_ is not None:
            kwargs.setdefault("trace_out", self.trace_out_)
        return TpuChecker(self, **kwargs)

    def run_supervised(
        self,
        engine: str = "resident",
        plan=None,
        config=None,
        checkpoint_path: str = None,
        **engine_kwargs,
    ):
        """Run this check under the self-healing supervisor
        (stateright_tpu/faults/): periodic atomic checkpoints, bounded
        retry with backoff, the degrade ladder, and the watchdog — with
        fault injection active when a `FaultPlan` is passed (or the
        `SR_TPU_FAULTS=` env is set). Blocking; returns the engine's
        `SearchResult` with recovery counters in `detail["faults"]`.
        Builder config (finish_when, targets) maps onto the run; the model
        must be a TensorModel, as on spawn_tpu."""
        from ..faults import run_supervised as _run_supervised
        from ..tensor.model import TensorModel

        if not isinstance(self.model, TensorModel):
            raise TypeError(
                "run_supervised requires a stateright_tpu.tensor."
                f"TensorModel; got {type(self.model).__name__}"
            )
        run_kwargs = {"finish_when": self.finish_when_}
        if self.target_state_count_ is not None:
            run_kwargs["target_state_count"] = self.target_state_count_
        if self.target_max_depth_ is not None:
            run_kwargs["target_max_depth"] = self.target_max_depth_
        return _run_supervised(
            self.model,
            engine=engine,
            plan=plan,
            config=config,
            checkpoint_path=checkpoint_path,
            engine_kwargs=engine_kwargs,
            run_kwargs=run_kwargs,
        )

    def spawn_service(self, service, priority: int = 0):
        """Submit this check as a JOB on a shared `CheckService` (the
        continuous-batching multi-job scheduler, stateright_tpu/service/)
        and return the same `Checker` handle surface `spawn_tpu` gives —
        except the device state tables are shared with every other job the
        service is running. Builder config (finish_when, targets, timeout)
        maps onto the job options; visitors/symmetry_fn are unsupported,
        as on spawn_tpu."""
        if self.visitor_ is not None:
            raise NotImplementedError(
                "visitors are not supported on service jobs; use spawn_tpu"
            )
        if self.symmetry_fn_ is not None:
            raise NotImplementedError(
                "symmetry_fn is host-level; device symmetry is the "
                "TensorModel.representative kernel (see spawn_tpu)"
            )
        handle = service.submit(
            self.model,
            finish_when=self.finish_when_,
            target_state_count=self.target_state_count_,
            target_max_depth=self.target_max_depth_,
            timeout=self.timeout_,
            priority=priority,
        )
        return handle.as_checker()
