"""On-demand (lazy BFS) checker — the engine behind the Explorer
(ref: src/checker/on_demand.rs).

Where the eager checkers race to exhaustion, this one expands states only
when asked: a background worker blocks on a control channel and handles
`CheckFingerprint(fp)` (expand that single known state) and
`RunToCompletion` (switch to ordinary BFS until the space is exhausted)
messages — the same control-flow protocol the reference threads wait on
(ref: src/checker/on_demand.rs:136-177, 406-415). Property evaluation,
eventually-bit bookkeeping, dedup-with-parent-pointers, and boundary/depth
cutoffs are shared with the eager BFS checker so verdicts agree.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Optional

from ..core.fingerprint import Fingerprint, fingerprint
from ..core.model import Expectation
from ..core.path import Path
from ._search import evaluate_properties, record_terminal_ebits
from .base import Checker


class OnDemandChecker(Checker):
    def __init__(self, options):
        super().__init__(options.model)
        model = options.model
        self._lock = threading.Lock()
        self._properties = model.properties()
        self._visitor = options.visitor_
        self._finish_when = options.finish_when_
        self._target_state_count = options.target_state_count_
        self._target_max_depth = options.target_max_depth_

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._max_depth = 0
        self._generated: dict[Fingerprint, Optional[Fingerprint]] = {}
        self._discoveries: dict[str, Fingerprint] = {}
        # Pending (unexpanded) states by fingerprint, so CheckFingerprint can
        # find its target; insertion order preserves BFS order for
        # RunToCompletion (dicts are ordered).
        self._jobs: dict[Fingerprint, tuple] = {}

        ebits = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        for s in init_states:
            fp = fingerprint(s)
            if fp not in self._generated:
                self._generated[fp] = None
                self._jobs[fp] = (s, ebits, 1)

        self._control: queue.Queue = queue.Queue()
        self._ran_to_completion = False
        self._closed = False
        self._panic: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._worker, name="on-demand-checker", daemon=True
        )
        self._thread.start()

    # -- control channel (ref: src/checker/on_demand.rs:406-415) ---------------

    def check_fingerprint(self, fingerprint: Fingerprint) -> None:
        """Ask the worker to expand the pending state with this fingerprint
        (no-op if unknown or already expanded)."""
        self._control.put(("check", fingerprint))

    def run_to_completion(self) -> None:
        self._control.put(("run", None))

    # -- worker ----------------------------------------------------------------

    def _worker(self) -> None:
        try:
            while True:
                msg, arg = self._control.get()
                if msg == "close":
                    return
                if msg == "check":
                    with self._lock:
                        job = self._jobs.pop(arg, None)
                    if job is not None:
                        state, ebits, depth = job
                        self._expand(state, arg, ebits, depth)
                elif msg == "run":
                    self._run_all()
                    self._ran_to_completion = True
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced by join()
            self._panic = e
        finally:
            self._closed = True

    def _run_all(self) -> None:
        """Ordinary BFS over whatever is still pending
        (ref: on_demand.rs RunToCompletion handling)."""
        while True:
            with self._lock:
                if not self._jobs:
                    return
                fp, (state, ebits, depth) = next(iter(self._jobs.items()))
                del self._jobs[fp]
            self._expand(state, fp, ebits, depth)
            if len(self._discoveries) == len(self._properties) and self._properties:
                return
            if self._finish_when.matches(self._properties, set(self._discoveries)):
                return
            if (
                self._target_state_count is not None
                and self._target_state_count <= self._state_count
            ):
                return

    def _expand(self, state, state_fp, ebits, depth) -> None:
        """Evaluate + expand ONE state; successors become pending jobs.
        Mirrors one iteration of the BFS hot loop (src/checker/bfs.rs:196-334)."""
        model = self._model
        if depth > self._max_depth:
            with self._lock:
                self._max_depth = max(self._max_depth, depth)
        if self._target_max_depth is not None and depth >= self._target_max_depth:
            return
        if self._visitor is not None and self._visitor.should_visit():
            # should_visit lets rate-limited visitors (the Explorer's
            # recent-path snapshot) skip the O(depth) path reconstruction
            # entirely between windows.
            self._visitor.visit(model, self._reconstruct_path(state_fp))
        is_awaiting, ebits = evaluate_properties(
            model, self._properties, state, self._discoveries, self._lock,
            state_fp, ebits,
        )
        if not is_awaiting:
            return
        is_terminal = True
        actions: list = []
        model.actions(state, actions)
        for action in actions:
            next_state = model.next_state(state, action)
            if next_state is None:
                continue
            if not model.within_boundary(next_state):
                continue
            with self._lock:
                self._state_count += 1
                next_fp = fingerprint(next_state)
                if next_fp in self._generated:
                    is_terminal = False
                    continue
                self._generated[next_fp] = state_fp
                self._jobs[next_fp] = (next_state, ebits, depth + 1)
            is_terminal = False
        if is_terminal:
            record_terminal_ebits(
                self._properties, ebits, self._discoveries, self._lock, state_fp
            )

    # -- Checker interface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        with self._lock:
            return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> dict[str, Path]:
        with self._lock:
            items = list(self._discoveries.items())
        return {name: self._reconstruct_path(fp) for name, fp in items}

    def join(self) -> "OnDemandChecker":
        """Joining an on-demand check runs it to completion first (a blocked
        lazy checker would otherwise never finish)."""
        if not self._closed:
            self.run_to_completion()
        self._thread.join()
        if self._panic is not None:
            raise self._panic
        return self

    def is_done(self) -> bool:
        if self._panic is not None or self._ran_to_completion:
            return True
        if self._properties and len(self._discoveries) == len(self._properties):
            return True
        with self._lock:
            return not self._jobs

    def _reconstruct_path(self, fp: Fingerprint) -> Path:
        fingerprints: deque = deque()
        next_fp: Optional[Fingerprint] = fp
        while next_fp is not None:
            with self._lock:
                if next_fp not in self._generated:
                    break
                source = self._generated[next_fp]
            fingerprints.appendleft(next_fp)
            next_fp = source
        return Path.from_fingerprints(self._model, list(fingerprints))
