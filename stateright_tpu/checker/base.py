"""The `Checker` interface: a handle to a (possibly still running) check
(ref: src/checker.rs:294-578).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from ..core.fingerprint import Fingerprint
from ..core.model import Expectation
from ..core.path import Path
from ..core.report import ReportData, Reporter


class DiscoveryClassification:
    EXAMPLE = "example"
    COUNTEREXAMPLE = "counterexample"


class Checker:
    """Base for all checker runtimes. Subclasses implement the counters,
    `discoveries`, `join`, and `is_done`."""

    def __init__(self, model):
        self._model = model

    # -- core surface ----------------------------------------------------------

    @property
    def model(self):
        return self._model

    def state_count(self) -> int:
        """Total states generated including repeats (ref: src/checker.rs:308)."""
        raise NotImplementedError

    def unique_state_count(self) -> int:
        """Unique states generated (ref: src/checker.rs:312)."""
        raise NotImplementedError

    def max_depth(self) -> int:
        """Deepest depth explored (ref: src/checker.rs:317)."""
        raise NotImplementedError

    def discoveries(self) -> dict[str, Path]:
        """Map from property name to discovery path (ref: src/checker.rs:321)."""
        raise NotImplementedError

    def join(self) -> "Checker":
        """Block until checking completes (ref: src/checker.rs:327-335)."""
        raise NotImplementedError

    def is_done(self) -> bool:
        """All properties have discoveries or all reachable states visited
        (ref: src/checker.rs:342)."""
        raise NotImplementedError

    # -- on-demand hooks (ref: src/checker.rs:299-306) -------------------------

    def check_fingerprint(self, fingerprint: Fingerprint) -> None:
        pass

    def run_to_completion(self) -> None:
        pass

    # -- state-store introspection ---------------------------------------------

    def store_stats(self) -> Optional[dict]:
        """Per-tier occupancy counters of the checker's state store (the
        TPU engines' tiered store reports hot_fill / spilled_states /
        spill_events here); None for single-tier checkers."""
        return None

    def table_fill(self) -> Optional[float]:
        """Visited-table fill fraction (0..1) when the checker can report
        it cheaply; None otherwise. Feeds the WriteReporter `fill=` field
        and `/metrics`."""
        return None

    def drift_ratio(self) -> Optional[float]:
        """Measured/predicted step-cost ratio from the calibration
        comparator (obs/calib.py) when the checker runs one; None
        otherwise. Feeds the WriteReporter `drift=` field."""
        return None

    # -- conveniences ----------------------------------------------------------

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def discovery_classification(self, name: str) -> str:
        """"example" vs "counterexample" (ref: src/checker.rs:455-464)."""
        prop = self._model.property_by_name(name)
        if prop.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY):
            return DiscoveryClassification.COUNTEREXAMPLE
        return DiscoveryClassification.EXAMPLE

    def report(self, reporter: Reporter) -> "Checker":
        """Periodically emit status until done, then a final line plus the
        discovery summary (ref: src/checker.rs:412-452)."""
        start = time.monotonic()
        prev: Optional[tuple] = None  # (states, t) of the previous tick
        while not self.is_done():
            now = time.monotonic()
            states = self.state_count()
            # rate: states/sec over the last reporting window (telemetry
            # satellite) — the live-progress twin of the bench's
            # states_per_sec, without waiting for the Done line. The first
            # tick has no window yet (the search started before this loop),
            # so it reports no rate rather than a microsecond-window blowup.
            rate = (
                (states - prev[0]) / max(now - prev[1], 1e-9)
                if prev is not None
                else None
            )
            prev = (states, now)
            reporter.report_checking(
                ReportData(
                    total_states=states,
                    unique_states=self.unique_state_count(),
                    max_depth=self.max_depth(),
                    duration=now - start,
                    done=False,
                    rate=rate,
                    fill=self.table_fill(),
                    drift=self.drift_ratio(),
                )
            )
            time.sleep(reporter.delay())
        self.join()
        reporter.report_checking(
            ReportData(
                total_states=self.state_count(),
                unique_states=self.unique_state_count(),
                max_depth=self.max_depth(),
                duration=time.monotonic() - start,
                done=True,
            )
        )
        discoveries = {
            name: (self.discovery_classification(name), path)
            for name, path in self.discoveries().items()
        }
        reporter.report_discoveries(self._model, discoveries)
        return self

    def join_and_report(self, reporter: Reporter) -> "Checker":
        """Like `report` but joins concurrently for an accurate finish time
        (ref: src/checker.rs:351-409). With Python's GIL the polling loop in
        `report` already behaves this way, so this is an alias."""
        return self.report(reporter)

    # -- assertion helpers (test oracle API; ref: src/checker.rs:468-577) ------

    def assert_properties(self) -> None:
        for p in self._model.properties():
            if p.expectation == Expectation.SOMETIMES:
                self.assert_any_discovery(p.name)
            else:
                self.assert_no_discovery(p.name)

    def assert_any_discovery(self, name: str) -> Path:
        found = self.discovery(name)
        if found is not None:
            return found
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )
        raise AssertionError(f'Discovery for "{name}" not found.')

    def assert_no_discovery(self, name: str) -> None:
        found = self.discovery(name)
        if found is not None:
            raise AssertionError(
                f'Unexpected "{name}" {self.discovery_classification(name)} '
                f"{found.format(self._model)}\nLast state: {found.last_state()!r}"
            )
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )

    def assert_discovery(self, name: str, actions: Sequence) -> None:
        """Panics unless `actions` also constitutes a valid discovery for the
        property, validated by re-execution (ref: src/checker.rs:521-577)."""
        additional_info: list[str] = []
        found = self.assert_any_discovery(name)
        model = self._model
        prop = model.property_by_name(name)
        for init_state in model.init_states():
            path = Path.from_actions(model, init_state, actions)
            if path is None:
                continue
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, path.last_state()):
                    return
            elif prop.expectation == Expectation.EVENTUALLY:
                states = path.states()
                liveness_satisfied = any(
                    prop.condition(model, s) for s in states
                )
                last_actions: list = []
                model.actions(states[-1], last_actions)
                path_terminal = not last_actions
                if not liveness_satisfied and path_terminal:
                    return
                if liveness_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property"
                    )
                if not path_terminal:
                    additional_info.append("incorrect counterexample is nonterminal")
            else:  # SOMETIMES
                if prop.condition(model, path.last_state()):
                    return
        extra = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{extra}, but a valid one was found. '
            f"found={found.actions()!r}"
        )
