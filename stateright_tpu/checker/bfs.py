"""Host breadth-first checker (ref: src/checker/bfs.rs).

Finds the shortest path to each discovery when single-threaded. Dedup is a
shared `{fingerprint: parent_fingerprint}` map whose parent pointers drive path
reconstruction (the TLC fingerprint-stack technique, ref: src/checker/bfs.rs:380-409).

This is the correctness oracle and API twin of the TPU frontier checker
(`stateright_tpu.checker.tpu`); the semantics here — property evaluation on each
unique state, eventually-bits lifecycle, boundary/depth/target cutoffs, including
the reference's documented DAG-join/cycle false negatives for `eventually`
(ref: src/checker.rs:580-587) — are the contract both must satisfy.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from ..core.fingerprint import Fingerprint, fingerprint
from ..core.model import Expectation
from ..core.path import Path
from itertools import islice

from ._search import (
    WorkerLoopMixin,
    evaluate_properties,
    plane_activity,
    prefetch_block_verdicts,
    state_carries_tester,
    record_terminal_ebits,
)
from .base import Checker
from .job_market import JobBroker


class BfsChecker(WorkerLoopMixin, Checker):
    BLOCK_SIZE = 1500  # states per block before re-sync (ref: src/checker/bfs.rs:130)

    def __init__(self, options):
        super().__init__(options.model)
        model = options.model
        self._lock = threading.Lock()
        self._properties = model.properties()
        self._visitor = options.visitor_
        self._finish_when = options.finish_when_
        self._target_state_count = options.target_state_count_
        self._target_max_depth = options.target_max_depth_

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._max_depth = 0
        # fp -> parent fp (None for init states); doubles as the visited set
        # (ref: src/checker/bfs.rs:29-30, 56-62).
        self._generated: dict[Fingerprint, Optional[Fingerprint]] = {}
        self._discoveries: dict[str, Fingerprint] = {}

        ebits = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        pending = deque()
        for s in init_states:
            fp = fingerprint(s)
            self._generated[fp] = None
            pending.append((s, fp, ebits, 1))

        self._broker: JobBroker = JobBroker.new(options.thread_count_, options.close_at)
        self._broker.push(pending)
        self._threads = []
        for t in range(options.thread_count_):
            th = threading.Thread(target=self._worker, name=f"checker-{t}", daemon=True)
            th.start()
            self._threads.append(th)

    def _check_block(self, pending: deque, max_count: int) -> None:
        """The hot loop (ref: src/checker/bfs.rs:177-335). Each popped state:
        depth bookkeeping, visitor, property evaluation, expansion with dedup."""
        model = self._model
        properties = self._properties
        # Chunk-boundary verdict prefetch (dedup-first semantics): resolve
        # the block's consistency-tester verdicts in one batched call before
        # the serial per-state loop below probes them. Feedback-gated: once
        # a prefetched block's property loop consults the plane zero times
        # (the consistency property has its discovery, or no property reads
        # the testers), prefetching stops — speculative searches the
        # pre-plane checker never ran must not outlive their consumer.
        probe_mark = None
        if getattr(self, "_plane_prefetch", True) and pending:
            if not state_carries_tester(pending[-1][0]):
                # Tester-less model: prefetching can never pay off — disable
                # before ever materializing a block copy.
                self._plane_prefetch = False
            else:
                prefetched = prefetch_block_verdicts(
                    list(islice(reversed(pending), max_count))
                )
                if prefetched:
                    probe_mark = plane_activity()
        while max_count > 0 and pending:
            max_count -= 1
            state, state_fp, ebits, depth = pending.pop()

            if depth > self._max_depth:
                with self._lock:
                    self._max_depth = max(self._max_depth, depth)
            if self._target_max_depth is not None and depth >= self._target_max_depth:
                continue

            if self._visitor is not None and self._visitor.should_visit():
                self._visitor.visit(model, self._reconstruct_path(state_fp))

            is_awaiting_discoveries, ebits = evaluate_properties(
                model, properties, state, self._discoveries, self._lock, state_fp, ebits
            )
            if not is_awaiting_discoveries:
                return

            is_terminal = True
            actions: list = []
            model.actions(state, actions)
            for action in actions:
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                with self._lock:
                    self._state_count += 1
                next_fp = fingerprint(next_state)
                with self._lock:
                    if next_fp in self._generated:
                        # Revisit: may be a cycle or a DAG join. Like the
                        # reference, treat as non-terminal and do not merge
                        # ebits — the documented eventually-property false
                        # negative (ref: src/checker/bfs.rs:293-315).
                        is_terminal = False
                        continue
                    self._generated[next_fp] = state_fp
                is_terminal = False
                pending.appendleft((next_state, next_fp, ebits, depth + 1))
            if is_terminal:
                record_terminal_ebits(
                    properties, ebits, self._discoveries, self._lock, state_fp
                )
        if probe_mark is not None and plane_activity() == probe_mark:
            self._plane_prefetch = False  # block went unconsumed: stop

    # -- Checker interface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> dict[str, Path]:
        with self._lock:
            items = list(self._discoveries.items())
        return {name: self._reconstruct_path(fp) for name, fp in items}

    def join(self) -> "BfsChecker":
        for th in self._threads:
            th.join()
        if self._broker.market.panic is not None:
            raise self._broker.market.panic
        return self

    def is_done(self) -> bool:
        return self._broker.is_closed() or len(self._discoveries) == len(
            self._properties
        ) or all(not th.is_alive() for th in self._threads)

    def _reconstruct_path(self, fp: Fingerprint) -> Path:
        """Walk parent pointers to the init state, then re-execute
        (ref: src/checker/bfs.rs:380-409)."""
        fingerprints: deque = deque()
        next_fp: Optional[Fingerprint] = fp
        while next_fp is not None:
            with self._lock:
                if next_fp not in self._generated:
                    break
                source = self._generated[next_fp]
            fingerprints.appendleft(next_fp)
            next_fp = source
        return Path.from_fingerprints(self._model, list(fingerprints))
