"""Shared search-loop machinery for the host checkers.

BFS and DFS are semantic twins differing only in queue discipline and dedup
bookkeeping (the reference keeps two near-identical files and defers the lift
until DPOR, ref: src/checker/bfs.rs:17-18); here the worker shutdown protocol
and the property/ebits evaluation — the parts that MUST stay in lockstep — live
in one place.
"""

from __future__ import annotations

from ..core.model import Expectation


class WorkerLoopMixin:
    """The per-thread job loop (ref: src/checker/bfs.rs:103-160 and the
    identical src/checker/dfs.rs:106-164).

    Hosts must provide: _broker, _lock, _properties, _discoveries,
    _finish_when, _target_state_count, _state_count, and _check_block.
    """

    def _worker(self) -> None:
        broker = self._broker
        panic = None
        try:
            from collections import deque

            pending = deque()
            while True:
                if not pending:
                    pending = broker.pop()
                    if not pending:
                        return
                self._check_block(pending, self.BLOCK_SIZE)
                if broker.deadline_passed():
                    return
                with self._lock:
                    discovered = set(self._discoveries)
                if self._finish_when.matches(self._properties, discovered):
                    return
                if (
                    self._target_state_count is not None
                    and self._target_state_count <= self._state_count
                ):
                    return
                if len(pending) > 1:
                    broker.split_and_push(pending)
        except BaseException as e:  # noqa: BLE001 — propagate via join()
            panic = e
        finally:
            # Any exit — early finish or panic — closes the market so peers
            # stop too (the reference does this in JobBroker::drop).
            broker.thread_exited(panic=panic)


def evaluate_properties(model, properties, state, discoveries, lock, token, ebits):
    """Evaluate every undiscovered property on `state`
    (ref: src/checker/bfs.rs:230-280 == dfs.rs:234-281 == simulation.rs:305-352).

    `token` is what a discovery records (BFS: the state's fingerprint; DFS and
    simulation: the full fingerprint path). Returns
    ``(is_awaiting_discoveries, ebits)`` where `ebits` has the indices of
    `eventually` properties observed on this state removed.
    """
    is_awaiting = False
    for i, prop in enumerate(properties):
        if prop.name in discoveries:
            continue
        if prop.expectation == Expectation.ALWAYS:
            if not prop.condition(model, state):
                with lock:
                    discoveries.setdefault(prop.name, token)
            else:
                is_awaiting = True
        elif prop.expectation == Expectation.SOMETIMES:
            if prop.condition(model, state):
                with lock:
                    discoveries.setdefault(prop.name, token)
            else:
                is_awaiting = True
        else:
            # EVENTUALLY discoveries are only identified at terminal states; a
            # satisfying state merely clears the path's pending bit.
            is_awaiting = True
            if prop.condition(model, state):
                ebits = ebits - {i}
    return is_awaiting, ebits


def record_terminal_ebits(properties, ebits, discoveries, lock, token) -> None:
    """At a terminal state, every still-set eventually bit is a counterexample
    (ref: src/checker/bfs.rs:326-333)."""
    for i, prop in enumerate(properties):
        if i in ebits:
            with lock:
                discoveries.setdefault(prop.name, token)
