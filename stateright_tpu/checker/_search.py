"""Shared search-loop machinery for the host checkers.

BFS and DFS are semantic twins differing only in queue discipline and dedup
bookkeeping (the reference keeps two near-identical files and defers the lift
until DPOR, ref: src/checker/bfs.rs:17-18); here the worker shutdown protocol
and the property/ebits evaluation — the parts that MUST stay in lockstep — live
in one place.
"""

from __future__ import annotations

from ..core.model import Expectation


def plane_activity() -> int:
    """Monotonic count of THIS thread's dedup-first-plane consultations —
    the feedback signal for the prefetch gate below: if a whole prefetched
    block's serial property loop moves this by nothing, the properties no
    longer consult the plane (the consistency property already has a
    discovery, or never existed) and prefetching would be pure speculative
    search work the pre-plane checker never did. Thread-local on purpose:
    sibling worker threads' consultations must not mask this worker's block
    going unconsumed."""
    from ..semantics.canonical import local_consultations

    return local_consultations()


def state_carries_tester(state) -> bool:
    """Whether a state's `.history` is a consistency tester — the one-time
    peek that decides if block prefetching can ever pay off for this model
    (checked on the next-popped state BEFORE materializing a block copy)."""
    from ..semantics import ConsistencyTester

    return isinstance(getattr(state, "history", None), ConsistencyTester)


def prefetch_block_verdicts(block) -> int:
    """Dedup-first semantics plane (semantics/batch.py): before a worker
    walks a block of states one-by-one, gather the block's consistency
    testers (actor-model states carry one as `.history`) and resolve their
    verdicts in ONE batched call — canonical-class collapse + witness
    guidance + (native) parallel search — so the per-state property lambdas
    hit a warm cache instead of probing (and too often searching) serially
    mid-loop. Pure optimization: property evaluation still decides on its
    own; a model without testers costs one getattr on the first state."""
    if not block:
        return 0
    probe = getattr(block[0][0], "history", None)
    from ..semantics import ConsistencyTester

    if not isinstance(probe, ConsistencyTester):
        return 0
    from ..semantics.batch import prefetch_verdicts

    return prefetch_verdicts(
        h
        for h in (getattr(item[0], "history", None) for item in block)
        if isinstance(h, ConsistencyTester)
    )


class WorkerLoopMixin:
    """The per-thread job loop (ref: src/checker/bfs.rs:103-160 and the
    identical src/checker/dfs.rs:106-164).

    Hosts must provide: _broker, _lock, _properties, _discoveries,
    _finish_when, _target_state_count, _state_count, and _check_block.
    """

    def _worker(self) -> None:
        broker = self._broker
        panic = None
        try:
            from collections import deque

            pending = deque()
            while True:
                if not pending:
                    pending = broker.pop()
                    if not pending:
                        return
                self._check_block(pending, self.BLOCK_SIZE)
                if broker.deadline_passed():
                    return
                with self._lock:
                    discovered = set(self._discoveries)
                if self._finish_when.matches(self._properties, discovered):
                    return
                if (
                    self._target_state_count is not None
                    and self._target_state_count <= self._state_count
                ):
                    return
                if len(pending) > 1:
                    broker.split_and_push(pending)
        except BaseException as e:  # noqa: BLE001 — propagate via join()
            panic = e
        finally:
            # Any exit — early finish or panic — closes the market so peers
            # stop too (the reference does this in JobBroker::drop).
            broker.thread_exited(panic=panic)


def evaluate_properties(model, properties, state, discoveries, lock, token, ebits):
    """Evaluate every undiscovered property on `state`
    (ref: src/checker/bfs.rs:230-280 == dfs.rs:234-281 == simulation.rs:305-352).

    `token` is what a discovery records (BFS: the state's fingerprint; DFS and
    simulation: the full fingerprint path). Returns
    ``(is_awaiting_discoveries, ebits)`` where `ebits` has the indices of
    `eventually` properties observed on this state removed.
    """
    is_awaiting = False
    for i, prop in enumerate(properties):
        if prop.name in discoveries:
            continue
        if prop.expectation == Expectation.ALWAYS:
            if not prop.condition(model, state):
                with lock:
                    discoveries.setdefault(prop.name, token)
            else:
                is_awaiting = True
        elif prop.expectation == Expectation.SOMETIMES:
            if prop.condition(model, state):
                with lock:
                    discoveries.setdefault(prop.name, token)
            else:
                is_awaiting = True
        else:
            # EVENTUALLY discoveries are only identified at terminal states; a
            # satisfying state merely clears the path's pending bit.
            is_awaiting = True
            if prop.condition(model, state):
                ebits = ebits - {i}
    return is_awaiting, ebits


def record_terminal_ebits(properties, ebits, discoveries, lock, token) -> None:
    """At a terminal state, every still-set eventually bit is a counterexample
    (ref: src/checker/bfs.rs:326-333)."""
    for i, prop in enumerate(properties):
        if i in ebits:
            with lock:
                discoveries.setdefault(prop.name, token)
