"""Symmetry reduction (ref: src/checker/{representative,rewrite,rewrite_plan}.rs).

Many actor systems are invariant under permutations of actor identity: checking
one member of each equivalence class ("representative") can shrink the state
space dramatically (the Symmetric-Spin technique the reference cites at
src/checker/representative.rs:7-16; e.g. 2PC with 5 RMs: 8,832 → 665 states).

`RewritePlan.from_values_to_sort` derives the canonicalizing permutation by
sorting values — a double argsort (ref: src/checker/rewrite_plan.rs:81-107),
which is exactly the argsort+gather shape the device canonicalization kernel
uses in `stateright_tpu.tensor.symmetry`.

`rewrite(value, plan)` structurally recurses, remapping every `Id` it finds
(ref: src/checker/rewrite.rs). Scalars pass through; `Timers` contents are
deliberately NOT rewritten, matching the reference's clone-only impl
(ref: src/actor/timers.rs:46-53).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

from ..core.fingerprint import stable_encode
from ..actor import Id


class Representative:
    """States implementing `representative()` opt into symmetry reduction via
    `CheckerBuilder.symmetry()` (ref: src/checker/representative.rs:65-68)."""

    def representative(self):
        raise NotImplementedError


class RewritePlan:
    """A permutation of dense-nat `Id`s derived by sorting values
    (ref: src/checker/rewrite_plan.rs)."""

    __slots__ = ("order", "inverse")

    def __init__(self, order: Sequence[int], inverse: Sequence[int]):
        self.order = tuple(order)  # new index -> old index
        self.inverse = tuple(inverse)  # old id -> new id

    @staticmethod
    def from_values_to_sort(values: Sequence) -> "RewritePlan":
        """Plan that sorts `values` (by canonical encoding — any total order
        yields a valid canonical form; ref: src/checker/rewrite_plan.rs:81-107)."""
        order = sorted(range(len(values)), key=lambda i: stable_encode(values[i]))
        inverse = [0] * len(order)
        for new_i, old_i in enumerate(order):
            inverse[old_i] = new_i
        return RewritePlan(order, inverse)

    def reindex(self, seq: Sequence) -> tuple:
        """Permute a vec-like indexed by actor id (ref: rewrite_plan.rs:110-124)."""
        return tuple(seq[i] for i in self.order)

    def rewrite_id(self, id: Id) -> Id:
        return Id(self.inverse[int(id)])

    def __repr__(self):
        return f"RewritePlan(order={self.order!r})"


def rewrite(value: Any, plan: RewritePlan) -> Any:
    """Structural recursion applying a plan (ref: src/checker/rewrite.rs).

    - `Id` values are remapped; all other scalars pass through unchanged.
    - Containers recurse (tuple/list/set/frozenset/dict).
    - `Envelope`s and frozen dataclasses recurse over fields.
    - Objects may customize via `__rewrite__(plan)` (e.g. `Network`).
    """
    if isinstance(value, Id):
        return plan.rewrite_id(value)
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    if hasattr(value, "__rewrite__"):
        return value.__rewrite__(plan)
    if isinstance(value, tuple):
        return tuple(rewrite(v, plan) for v in value)
    if isinstance(value, list):
        return [rewrite(v, plan) for v in value]
    if isinstance(value, frozenset):
        return frozenset(rewrite(v, plan) for v in value)
    if isinstance(value, set):
        return {rewrite(v, plan) for v in value}
    if isinstance(value, dict):
        return {rewrite(k, plan): rewrite(v, plan) for k, v in value.items()}
    if dataclasses.is_dataclass(value):
        return type(value)(
            **{
                f.name: rewrite(getattr(value, f.name), plan)
                for f in dataclasses.fields(value)
            }
        )
    return value  # opaque: pass through (mirrors the reference's no-op impls)
