"""HTTP front end of the check service (the Explorer server plumbing,
repointed at the multi-job scheduler).

Endpoints:

- ``GET /.status`` — service counters + one summary row per job (queue
  wait, lanes held, preemptions, per-tier store occupancy, step-telemetry
  digest — the service twin of the Explorer's `/.status`).
- ``GET /metrics`` — every registered counter source (the obs registry:
  this service, any live engines, ...) in Prometheus text exposition
  format, scrape-ready.
- ``POST /jobs`` — submit a job: ``{"model": "<registry name>", "args":
  {...}, "opts": {"target_max_depth": ..., "timeout": ..., "priority":
  ...}}`` → ``{"job": id}``. Models are named through a REGISTRY of
  builder callables (HTTP clients cannot ship Python model objects); the
  default registry carries the bundled tensor workloads.
- ``GET /jobs/<id>`` — poll one job (status, counts, discovery names,
  metrics).
- ``POST /jobs/<id>/cancel`` / ``DELETE /jobs/<id>`` — cancel.
- ``POST /jobs/<id>/withdraw`` — atomically remove a still-QUEUED job
  (the fleet work-stealing primitive, exposed over HTTP so a remote
  router can steal exactly like an in-proc one); ``{"withdrawn": bool}``.
- ``GET /jobs/<id>/discoveries`` — the reconstructed discovery paths of a
  finished job (action-label lists, the `assert_discovery` currency).
- ``GET /jobs/<id>/events?since=N&wait=S`` — live flight-recorder tail
  (obs/events.py; the service must be built with ``events``/
  ``events_out``): journal events naming the job with cursor >= ``since``,
  long-polling up to ``wait`` seconds for the first match. The response's
  ``next`` is the cursor to pass back — the dashboard follow-a-job
  primitive.

The view builders are pure functions over the service, the same
test-without-sockets strategy as explorer/server.py.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional
from urllib.parse import parse_qs

from ..explorer.server import ExplorerServer
from ..faults.plan import FaultError, maybe_fault
from ..obs import REGISTRY, render_prometheus
from .api import CheckService
from .tenancy import DEFAULT_TENANT, QuotaExceeded

#: `Retry-After` seconds on every 503 this plane emits (injected faults,
#: router overload) — deterministic, so load clients back off identically
#: run to run instead of hot-looping.
RETRY_AFTER_S = "1"


def default_registry() -> dict:
    """Name -> model-builder callables for the bundled tensor workloads.
    Builders are cached per argument tuple so repeat submissions of the
    same config share one model instance — and therefore one compiled
    step and one batch (the continuous-batching win)."""
    from ..tensor.models import (
        TensorIncrementLock,
        TensorLinearEquation,
        TensorTwoPhaseSys,
    )
    from ..tensor.paxos import TensorPaxos

    reg: dict[str, Callable] = {
        "2pc": lambda n=3, **kw: TensorTwoPhaseSys(int(n), **kw),
        "paxos": lambda n=2, **kw: TensorPaxos(client_count=int(n), **kw),
        "inclock": lambda n=3, **kw: TensorIncrementLock(int(n), **kw),
        "lineq": lambda a=2, b=10, **kw: TensorLinearEquation(
            int(a), int(b), **kw
        ),
    }
    return reg


class ModelRegistry:
    """Instance-caching wrapper over builder callables (see
    default_registry): same (name, args) -> same model object."""

    def __init__(self, builders: Optional[dict] = None):
        self._builders = (
            dict(builders) if builders is not None else default_registry()
        )
        self._cache: dict = {}

    def names(self) -> list:
        return sorted(self._builders)

    def get(self, name: str, args: Optional[dict] = None):
        if name not in self._builders:
            raise KeyError(
                f"unknown model {name!r} (registered: {self.names()})"
            )
        args = dict(args or {})
        key = (name, tuple(sorted(args.items())))
        if key not in self._cache:
            self._cache[key] = self._builders[name](**args)
        return self._cache[key]


# -- pure view builders --------------------------------------------------------


def job_view(service: CheckService, job_id: int) -> dict:
    return service.poll(job_id)


def status_view(service: CheckService) -> dict:
    """JSON for `GET /.status`: service counters + per-job rows."""
    return {
        **service.stats(),
        "job_rows": [service.poll(jid) for jid in service.job_ids()],
    }


def metrics_view(service: CheckService) -> str:
    """Prometheus text for `GET /metrics`: every source in the obs
    registry. The served (live, strongly-referenced) service is already in
    the collection under its registered name; the fallback only fires if it
    was somehow unregistered (e.g. scrape racing close())."""
    groups = REGISTRY.collect()
    if service._metrics_name not in groups:
        groups[service._metrics_name] = service.metrics()
    return render_prometheus(groups)


def submit_view(
    service: CheckService, registry: ModelRegistry, payload: dict
) -> dict:
    from ..core.discovery import HasDiscoveries

    opts = dict(payload.get("opts") or {})
    fw = opts.pop("finish_when", None)
    if fw is not None:
        opts["finish_when"] = {
            "all": HasDiscoveries.ALL,
            "any": HasDiscoveries.ANY,
            "all_failures": HasDiscoveries.ALL_FAILURES,
            "any_failures": HasDiscoveries.ANY_FAILURES,
        }[fw]
    model = registry.get(payload["model"], payload.get("args"))
    tenant = payload.get("tenant") or DEFAULT_TENANT
    handle = service.submit(model, tenant=tenant, **opts)
    return {"job": handle.id}


def events_view(service, job_id: int, query: str) -> dict:
    """JSON for `GET /jobs/<id>/events?since=N&wait=S`: the flight-recorder
    long-poll (shared by serve_service and serve_fleet — `service` is
    anything with `events_tail`). Malformed cursors degrade to defaults —
    an observability endpoint must never 500 over a bad query."""
    q = parse_qs(query)
    try:
        since = int(q.get("since", ["0"])[0])
    except ValueError:
        since = 0
    try:
        # Cap the long-poll under common proxy/client timeouts.
        wait_s = max(0.0, min(float(q.get("wait", ["0"])[0]), 25.0))
    except ValueError:
        wait_s = 0.0
    events, nxt = service.events_tail(job_id, since=since, wait_s=wait_s)
    return {"events": events, "next": nxt}


def discoveries_view(service: CheckService, job_id: int) -> dict:
    job = service._get(job_id)
    paths = service.discovery_paths(job_id)
    return {
        name: {
            "fingerprint": str(job.discoveries[name]),
            "actions": [repr(a) for a in path.actions()],
            "last_state": repr(path.last_state()),
        }
        for name, path in paths.items()
    }


# -- HTTP plumbing -------------------------------------------------------------


def serve_service(
    service: CheckService,
    address: str = "localhost:3400",
    registry: Optional[ModelRegistry] = None,
    block: bool = False,
) -> ExplorerServer:
    """Start the HTTP front end; returns the same server handle shape as
    the Explorer's `serve` (shutdown() stops it)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else ModelRegistry()
    host, _, port = address.partition(":")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _json(self, obj, code=200, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _job_id(self, suffix: str = "") -> Optional[int]:
            raw = self.path.partition("?")[0][len("/jobs/"):]
            if suffix:
                if not raw.endswith(suffix):
                    return None
                raw = raw[: -len(suffix)]
            try:
                return int(raw.strip("/"))
            except ValueError:
                return None

        def _text(self, body: str, code=200):
            data = body.encode()
            self.send_response(code)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _injected_503(self, method: str) -> bool:
            """Chaos-plane boundary for the HTTP plane: an injected
            `service.http` fault degrades to a 503 (the retryable status
            clients already understand) instead of crashing the handler —
            the front end must stay up through its own faults. The
            `Retry-After` header is what lets the fleet router and load
            clients back off deterministically instead of hot-looping."""
            try:
                maybe_fault("service.http", method=method, path=self.path)
            except FaultError as e:
                self._json(
                    {"error": f"injected fault: {e}"}, 503,
                    headers={"Retry-After": RETRY_AFTER_S},
                )
                return True
            return False

        def do_GET(self):
            if self._injected_503("GET"):
                return
            path, _, query = self.path.partition("?")
            try:
                if path == "/.status":
                    self._json(status_view(service))
                    return
                if path == "/metrics":
                    self._text(metrics_view(service))
                    return
                if path.startswith("/jobs/"):
                    if path.endswith("/discoveries"):
                        jid = self._job_id("/discoveries")
                        if jid is not None:
                            self._json(discoveries_view(service, jid))
                            return
                    if path.endswith("/events"):
                        jid = self._job_id("/events")
                        if jid is not None:
                            service._get(jid)  # 404 on unknown jobs
                            self._json(events_view(service, jid, query))
                            return
                    jid = self._job_id()
                    if jid is not None:
                        self._json(job_view(service, jid))
                        return
                self._json({"error": "not found"}, 404)
            except KeyError as e:
                self._json({"error": str(e)}, 404)

        def do_POST(self):
            if self._injected_503("POST"):
                return
            try:
                if self.path == "/jobs":
                    n = int(self.headers.get("Content-Length") or 0)
                    try:
                        payload = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError:
                        self._json({"error": "bad JSON body"}, 400)
                        return
                    if "model" not in payload:
                        self._json({"error": "missing 'model'"}, 400)
                        return
                    try:
                        self._json(submit_view(service, reg, payload))
                    except QuotaExceeded as e:
                        # Over-quota is retryable by contract, not a bad
                        # request: 429 + a Retry-After computed from the
                        # tenant's actual refill rate, mirroring the 503
                        # discipline (clients back off, never hot-loop).
                        self._json(
                            {
                                "error": str(e),
                                "tenant": e.tenant,
                                "reason": e.reason,
                            },
                            429,
                            headers={"Retry-After": str(e.retry_after_s)},
                        )
                    return
                if self.path.startswith("/jobs/") and self.path.endswith(
                    "/cancel"
                ):
                    jid = self._job_id("/cancel")
                    if jid is not None:
                        self._json({"cancelled": service.cancel(jid)})
                        return
                if self.path.startswith("/jobs/") and self.path.endswith(
                    "/withdraw"
                ):
                    jid = self._job_id("/withdraw")
                    if jid is not None:
                        service._get(jid)  # 404 on unknown jobs
                        self._json({"withdrawn": service.withdraw(jid)})
                        return
                self._json({"error": "not found"}, 404)
            except KeyError as e:
                self._json({"error": str(e)}, 404)
            except Exception as e:  # noqa: BLE001 — bad submits must not kill
                self._json({"error": f"{type(e).__name__}: {e}"}, 400)

        def do_DELETE(self):
            if self._injected_503("DELETE"):
                return
            jid = self._job_id()
            if jid is None:
                self._json({"error": "not found"}, 404)
                return
            try:
                self._json({"cancelled": service.cancel(jid)})
            except KeyError as e:
                self._json({"error": str(e)}, 404)

    httpd = ThreadingHTTPServer(
        (host or "localhost", int(port or 3400)), Handler
    )
    if block:
        server = ExplorerServer(httpd, service, None)
        try:
            httpd.serve_forever()
        finally:
            httpd.server_close()
        return server
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return ExplorerServer(httpd, service, thread)
