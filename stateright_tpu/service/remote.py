"""HTTP-backed fleet replicas: the cross-process half of ROADMAP item 1.

The r13 `Replica` seam (submit / probe / withdraw) was deliberately shaped
like an RPC surface; this module backs it with a real one. A
`RemoteReplica` is the router-side stub: it speaks HTTP to a per-host
`replica_main` subprocess (one `Replica` driver over one CheckService,
served by `serve_replica`) and mirrors each submitted job's completion
state locally so the router's harvest/steal logic works unchanged. All
replicas share one store root — a local/NFS directory OR a
``blob://host:port`` object store (faults/blobstore.py):

    <root>/ckpt/     per-job checkpoint generations (faults/ckptio.py)
    <root>/leases/   the epoch-fence lease records (service/lease.py)
    <root>/journal/  per-writer flight-recorder journals (obs/events.py;
                     local-write, blob-synced at flush boundaries)
    <root>/members/  member-discovery records (service/discovery.py):
                     address, pid, lease epoch, heartbeat — the spawner
                     waits on them instead of port files, the router
                     re-discovers a rejoined incarnation's fresh address
                     from them, and the root URI becomes the fleet's
                     single shared configuration
    <root>/corpus/   (optional) the shared warm-start corpus

Local-only surfaces (child stdout/stderr logs, the local halves of the
journals) live in a per-host SCRATCH directory when the root is a blob
URI (the root itself when it is a filesystem path).

What crosses the HTTP boundary is deliberately small: model REFERENCES
(registry name + args — both sides resolve them through the same
ModelRegistry), job options, and checkpoint PATHS (`ResumeToken`) — never
array payloads. The serving process loads resume checkpoints itself
through `ckptio.fenced_load_latest`, so a zombie's stale generation is
rejected in whichever process the resume happens.

The ``fleet.partition`` chaos point fires at the top of every RemoteReplica
request: an injected partition makes one replica unreachable from the
router (probes fail, submissions fail over) while the replica process
keeps running — the false-positive death whose writes the lease fence
makes provably harmless.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from ..core.discovery import HasDiscoveries
from ..faults.blobstore import is_blob_uri
from ..faults.plan import maybe_fault
from ..obs import as_tracer
from ..tensor.frontier import SearchResult
from .queue import JobStatus
from .router import ReplicaDead, ResumeToken, lease_member

__all__ = [
    "RemoteReplica",
    "RemoteJobHandle",
    "serve_replica",
    "spawn_replica_proc",
]


def encode_finish_when(fw) -> Optional[dict]:
    if fw is None:
        return None
    return {"kind": fw.kind, "names": sorted(fw.names)}


def decode_finish_when(data) -> HasDiscoveries:
    if data is None:
        return HasDiscoveries.ALL
    return HasDiscoveries(str(data["kind"]), frozenset(data.get("names", ())))


def result_to_json(r: SearchResult) -> dict:
    """SearchResult -> wire form (discovery fingerprints as ints; detail
    passes through — it is already JSON-shaped by the schema contract)."""
    return {
        "state_count": int(r.state_count),
        "unique_state_count": int(r.unique_state_count),
        "max_depth": int(r.max_depth),
        "discoveries": {k: int(v) for k, v in r.discoveries.items()},
        "complete": bool(r.complete),
        "duration": float(r.duration),
        "steps": int(r.steps),
        "detail": r.detail,
    }


def result_from_json(data: dict) -> SearchResult:
    return SearchResult(
        state_count=int(data["state_count"]),
        unique_state_count=int(data["unique_state_count"]),
        max_depth=int(data["max_depth"]),
        discoveries={k: int(v) for k, v in data["discoveries"].items()},
        complete=bool(data["complete"]),
        duration=float(data["duration"]),
        steps=int(data.get("steps", 0)),
        detail=data.get("detail"),
    )


class _RemoteJobMirror:
    """Router-side completion mirror of one remote inner job — duck-types
    the `Job` fields the router's harvest/steal logic reads (`status`,
    `event`, `result`, `error`)."""

    __slots__ = ("status", "result", "error", "event")

    def __init__(self):
        self.status = JobStatus.QUEUED
        self.result = None
        self.error: Optional[str] = None
        self.event = threading.Event()


class RemoteJobHandle:
    """The remote twin of api.JobHandle, HTTP-backed. `_job` is the local
    mirror the owning RemoteReplica's poller keeps current."""

    def __init__(self, replica: "RemoteReplica", job_id: int):
        self._replica = replica
        self.id = job_id
        self._job = _RemoteJobMirror()

    def poll(self) -> dict:
        return self._replica._get_json(f"/jobs/{self.id}")

    def cancel(self) -> bool:
        out = self._replica._post_json(f"/jobs/{self.id}/cancel", {})
        return bool(out.get("cancelled"))

    def discoveries(self) -> dict:
        """{property name: discovery record} as served by the replica's
        `/jobs/<id>/discoveries` (action-label lists — the cross-process
        form of a reconstructed Path)."""
        return self._replica._get_json(f"/jobs/{self.id}/discoveries")


class RemoteReplica:
    """The Replica seam over HTTP. The router drives it exactly like an
    in-proc `Replica`; a background poller keeps each submitted job's
    completion mirror current (the event/result the router harvests)."""

    #: The router keys replica-kind behavior on this (resume tokens cross
    #: the wire as paths; model objects never do).
    remote = True

    def __init__(
        self,
        idx: int,
        base_url: str,
        proc: Optional[subprocess.Popen] = None,
        tracer=None,
        request_timeout_s: float = 10.0,
        probe_timeout_s: float = 2.0,
        control_timeout_s: float = 2.0,
        poll_interval_s: float = 0.02,
        store_root: Optional[str] = None,
    ):
        self.idx = idx
        self.base_url = base_url.rstrip("/")
        self.proc = proc
        self.error: Optional[str] = None
        # Address re-discovery (service/discovery.py): with a store root,
        # a failed probe re-resolves the member's published record — a
        # replica that restarted on a fresh port (rejoin without a
        # respawn, a host-local supervisor bouncing the process) is
        # reachable again without anyone re-wiring the router.
        self.store_root = store_root
        self.rediscoveries = 0
        self._next_rediscover = 0.0  # throttle: record reads cost retries
        self._adopted_ts = 0.0  # newest record ts adopted (stale guard)
        self.request_timeout_s = request_timeout_s
        self.probe_timeout_s = probe_timeout_s
        # Router-tick control ops (withdraw) get a SHORT deadline: a
        # hung/stopped replica must cost the tick loop seconds, not a full
        # request timeout per attempt — the probe cadence is what detects
        # its death, and it can only run between ticks.
        self.control_timeout_s = control_timeout_s
        self.poll_interval_s = poll_interval_s
        self._tracer = as_tracer(tracer)
        self._handles: dict[int, RemoteJobHandle] = {}
        self._lock = threading.Lock()
        self._last_probe: dict = {}
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- HTTP plumbing ---------------------------------------------------------

    def _request(self, path: str, body=None, timeout: Optional[float] = None):
        # Chaos-plane boundary: an injected `fleet.partition` makes this
        # replica unreachable from the router — the request never leaves.
        maybe_fault("fleet.partition", replica=self.idx)
        url = self.base_url + path
        if body is None:
            req = urllib.request.Request(url)
        else:
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(), method="POST",
                headers={"Content-Type": "application/json"},
            )
        with urllib.request.urlopen(
            req, timeout=timeout or self.request_timeout_s
        ) as resp:
            return json.loads(resp.read() or b"{}")

    def _get_json(self, path: str, timeout: Optional[float] = None):
        return self._request(path, timeout=timeout)

    def _post_json(self, path: str, body: dict):
        return self._request(path, body=body)

    # -- router-facing surface -------------------------------------------------

    @property
    def alive(self) -> bool:
        """Process liveness only (a kill -9 shows up here immediately);
        hangs and partitions are the router's probe deadline's business."""
        return self.proc is None or self.proc.poll() is None

    def submit(self, spec: dict, ckpt_path: Optional[str] = None):
        model_ref = spec.get("model_ref")
        if model_ref is None:
            raise ReplicaDead(
                f"replica {self.idx} is remote: submissions need "
                "model_ref=(registry name, args) — model objects cannot "
                "cross the process boundary"
            )
        name, args = model_ref
        resume = spec.get("resume")
        payload = {
            "model": name,
            "args": dict(args or {}),
            "opts": {
                "finish_when": encode_finish_when(spec.get("finish_when")),
                "target_state_count": spec.get("target_state_count"),
                "target_max_depth": spec.get("target_max_depth"),
                "timeout": spec.get("timeout"),
                "priority": spec.get("priority", 0),
                "tenant": spec.get("tenant", "default"),
            },
            "journal": bool(spec.get("journal")),
            "trace": spec.get("trace"),
            "resume_from": (
                resume.path if isinstance(resume, ResumeToken) else None
            ),
            "ckpt": ckpt_path,
        }
        try:
            out = self._post_json("/jobs", payload)
        except Exception as e:  # noqa: BLE001 — any transport/5xx failure
            raise ReplicaDead(
                f"replica {self.idx} submit failed: {type(e).__name__}: {e}"
            ) from e
        if "job" not in out:
            raise ReplicaDead(
                f"replica {self.idx} rejected the submission: {out}"
            )
        handle = RemoteJobHandle(self, int(out["job"]))
        with self._lock:
            self._handles[handle.id] = handle
        return handle

    def withdraw(self, inner_job_id: int) -> bool:
        try:
            out = self._request(
                f"/jobs/{inner_job_id}/withdraw", body={},
                timeout=self.control_timeout_s,
            )
        except Exception:  # noqa: BLE001 — unreachable replica: not stolen
            return False
        return bool(out.get("withdrawn"))

    def probe(self) -> dict:
        """GET /.probe under a short socket timeout: a SIGSTOPped or
        partitioned child times out here, which the router's deadline
        probe converts into suspicion and eventually a (possibly
        false-positive — that is what the lease fence is for) death. A
        transport failure additionally attempts ADDRESS RE-DISCOVERY
        from the store root's member record before reporting, so a
        replica serving at a fresh address answers the NEXT probe."""
        try:
            out = self._get_json("/.probe", timeout=self.probe_timeout_s)
        except Exception as e:  # noqa: BLE001 — any transport failure
            self._maybe_rediscover()
            raise ReplicaDead(
                f"replica {self.idx} probe failed: {type(e).__name__}: {e}"
            ) from e
        with self._lock:
            self._last_probe = out
        return out

    def _maybe_rediscover(self) -> None:
        """Re-resolve this member's address from its discovery record;
        best-effort (a missing/unreachable record changes nothing) and
        THROTTLED — it runs inside the probe-failure path, and paying the
        record read's bounded retry on every failed probe would multiply
        probe latency exactly when the store is also struggling."""
        if self.store_root is None:
            return
        now = time.monotonic()
        if now < self._next_rediscover:
            return
        self._next_rediscover = now + 5.0
        try:
            from .discovery import MemberDirectory
            from .router import lease_member

            rec = MemberDirectory(self.store_root).lookup(
                lease_member(self.idx)
            )
        except OSError:
            return
        if rec is None:
            return
        # Stale-record guard: `read_record_latest` serves `.prev` while
        # the current record is torn mid-rotation, and a stale LIST
        # window can do the same store-side — so a read here can return
        # an OLDER record than one we already adopted. Adopting it would
        # regress the address to a dead incarnation's port; records
        # carry the publisher's heartbeat `ts`, so only move forward.
        rec_ts = float(rec.get("ts", 0.0) or 0.0)
        if rec_ts < self._adopted_ts:
            return
        addr = str(rec.get("address", "")).rstrip("/")
        if addr and addr != self.base_url:
            with self._lock:
                self.base_url = addr
                self.rediscoveries += 1
                self._adopted_ts = rec_ts
            self._tracer.instant(
                "fleet.rediscover", cat="fleet", replica=self.idx,
                address=addr,
            )

    def idle(self) -> bool:
        with self._lock:
            p = dict(self._last_probe)
        return bool(self.alive and p.get("idle") and not p.get("queued"))

    def snapshot_row(self) -> dict:
        if not self.alive:
            return {"alive": 0, "error": self.error or "process exited"}
        with self._lock:
            p = dict(self._last_probe)
        return {
            "alive": 1,
            # Pre-first-probe (or partitioned-from-boot) the cache is
            # empty: report zeros, not None — stats() SUMS these rows.
            "queued": p.get("queued") or 0,
            "device_steps": p.get("device_steps") or 0,
            # Autoscaler signals ride the probe cache too (the serving
            # process's Replica.probe computes them lock-free).
            "lane_util": p.get("lane_util") or 0.0,
            "adm_p99_ms": p.get("adm_p99_ms") or 0.0,
            "remote": self.base_url,
        }

    # -- completion mirroring --------------------------------------------------

    def spin(self) -> int:
        """One mirror refresh over every unfinished handle; returns how
        many reached a terminal state. Driven by the poller thread (the
        remote analogue of the in-proc driver's pump loop)."""
        with self._lock:
            open_handles = [
                h for h in self._handles.values()
                if not h._job.event.is_set()
            ]
        done = 0
        for h in open_handles:
            try:
                p = h.poll()
            except Exception:  # noqa: BLE001 — probes own liveness verdicts
                continue
            status = p.get("status")
            if status not in JobStatus.FINISHED:
                h._job.status = status or h._job.status
                continue
            if status == JobStatus.DONE:
                try:
                    h._job.result = result_from_json(
                        self._get_json(f"/jobs/{h.id}/result")
                    )
                except Exception:  # noqa: BLE001 — retry on the next spin
                    continue
            h._job.error = p.get("error")
            h._job.status = status
            h._job.event.set()
            done += 1
        return done

    def _drive(self) -> None:
        while not self._stop:
            self.spin()
            time.sleep(self.poll_interval_s)

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._drive, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        self.stop()


# -- the serving side (runs inside replica_main) --------------------------------


def serve_replica(
    replica,
    address: str = "localhost:0",
    registry=None,
    lease_store=None,
):
    """HTTP server over one `Replica` driver — the per-host twin of
    `serve_service`, extended with the fleet-internal endpoints the router
    stub drives: `GET /.probe`, `POST /jobs` (model refs + resume paths +
    checkpoint registration), `POST /jobs/<id>/withdraw`, and
    `GET /jobs/<id>/result`. Returns the ExplorerServer-shaped handle."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ..explorer.server import ExplorerServer
    from ..obs import REGISTRY, render_prometheus
    from .lease import load_fenced_resume
    from .server import ModelRegistry, discoveries_view, events_view, status_view

    service = replica.service
    reg = registry if registry is not None else ModelRegistry()
    host, _, port = address.partition(":")

    def load_resume(path: Optional[str]):
        """Resolve a resume path against the shared store root through the
        fence: stale (revoked-epoch) generations are rejected and counted;
        nothing loadable means a fresh (still exact) restart."""
        if not path:
            return None
        return load_fenced_resume(path, lease_store)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _job_id(self, suffix: str = "") -> Optional[int]:
            raw = self.path.partition("?")[0][len("/jobs/"):]
            if suffix:
                if not raw.endswith(suffix):
                    return None
                raw = raw[: -len(suffix)]
            try:
                return int(raw.strip("/"))
            except ValueError:
                return None

        def do_GET(self):
            path, _, query = self.path.partition("?")
            try:
                if path == "/.probe":
                    try:
                        out = replica.probe()
                    except Exception as e:  # noqa: BLE001 — dead reads as 503
                        self._json(
                            {"error": f"{type(e).__name__}: {e}"}, 503
                        )
                        return
                    out["idle"] = replica.idle()
                    if lease_store is not None:
                        out["lease"] = lease_store.metrics()
                    self._json(out)
                    return
                if path == "/.status":
                    out = status_view(service)
                    out["replica"] = replica.snapshot_row()
                    if lease_store is not None:
                        out["lease"] = lease_store.metrics()
                    self._json(out)
                    return
                if path == "/metrics":
                    data = render_prometheus(REGISTRY.collect()).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if path.startswith("/jobs/"):
                    if path.endswith("/result"):
                        jid = self._job_id("/result")
                        if jid is not None:
                            job = service._get(jid)
                            if job.result is None:
                                self._json({"error": "not finished"}, 409)
                                return
                            self._json(result_to_json(job.result))
                            return
                    if path.endswith("/discoveries"):
                        jid = self._job_id("/discoveries")
                        if jid is not None:
                            self._json(discoveries_view(service, jid))
                            return
                    if path.endswith("/events"):
                        jid = self._job_id("/events")
                        if jid is not None:
                            service._get(jid)
                            self._json(events_view(service, jid, query))
                            return
                    jid = self._job_id()
                    if jid is not None:
                        self._json(service.poll(jid))
                        return
                self._json({"error": "not found"}, 404)
            except KeyError as e:
                self._json({"error": str(e)}, 404)

        def do_POST(self):
            try:
                if self.path == "/jobs":
                    n = int(self.headers.get("Content-Length") or 0)
                    try:
                        payload = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError:
                        self._json({"error": "bad JSON body"}, 400)
                        return
                    model = reg.get(
                        payload["model"], payload.get("args") or {}
                    )
                    opts = dict(payload.get("opts") or {})
                    spec = dict(
                        model=model,
                        finish_when=decode_finish_when(
                            opts.get("finish_when")
                        ),
                        target_state_count=opts.get("target_state_count"),
                        target_max_depth=opts.get("target_max_depth"),
                        timeout=opts.get("timeout"),
                        priority=int(opts.get("priority") or 0),
                        tenant=opts.get("tenant") or "default",
                        journal=bool(payload.get("journal")),
                        resume=load_resume(payload.get("resume_from")),
                        trace=payload.get("trace"),
                    )
                    try:
                        handle = replica.submit(spec, payload.get("ckpt"))
                    except ReplicaDead as e:
                        self._json({"error": str(e)}, 503)
                        return
                    self._json({"job": handle.id})
                    return
                if self.path.startswith("/jobs/"):
                    if self.path.endswith("/withdraw"):
                        jid = self._job_id("/withdraw")
                        if jid is not None:
                            self._json(
                                {"withdrawn": replica.withdraw(jid)}
                            )
                            return
                    if self.path.endswith("/cancel"):
                        jid = self._job_id("/cancel")
                        if jid is not None:
                            self._json({"cancelled": service.cancel(jid)})
                            return
                self._json({"error": "not found"}, 404)
            except KeyError as e:
                self._json({"error": str(e)}, 404)
            except Exception as e:  # noqa: BLE001 — bad submits must not kill
                self._json({"error": f"{type(e).__name__}: {e}"}, 400)

    httpd = ThreadingHTTPServer((host or "localhost", int(port or 0)), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return ExplorerServer(httpd, replica, thread)


# -- process spawning ----------------------------------------------------------


def spawn_replica_proc(
    idx: int,
    root: str,
    service_kwargs: dict,
    timeout_s: float = 180.0,
    env_extra: Optional[dict] = None,
    scratch: Optional[str] = None,
    incarnation: Optional[int] = None,
) -> tuple:
    """Launch one `replica_main` subprocess over the shared store root and
    wait for it to DISCOVER itself: the child publishes a
    ``members/member-replica<idx>.json`` record (service/discovery.py)
    into the root once its HTTP server is bound, and the spawner waits
    for a record whose ``pid`` matches the child it just forked — a stale
    record from a previous incarnation can never satisfy a fresh spawn.
    Works identically on filesystem and ``blob://`` roots (the point:
    the root URI is the only configuration the spawner and the child
    share). Returns `(Popen, base_url)`.

    `scratch` is the local directory for child logs and local-write
    journals (required when `root` is a blob URI; defaults to `root`).
    `incarnation` marks a REJOIN respawn: the child journals under the
    ``replica<idx>@e<epoch>`` writer so the restarted stream merges
    cleanly next to the fenced old incarnation's."""
    from .discovery import MemberDirectory

    member = lease_member(idx)
    scratch = scratch or root
    if is_blob_uri(scratch):
        raise ValueError(
            "spawn_replica_proc needs a LOCAL scratch dir for child "
            "logs/journals when the store root is a blob URI"
        )
    os.makedirs(os.path.join(scratch, "logs"), exist_ok=True)
    suffix = f".e{incarnation}" if incarnation else ""
    log_path = os.path.join(scratch, "logs", f"{member}{suffix}.log")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra or {})
    cmd = [
        sys.executable, "-m", "stateright_tpu.service.replica_main",
        "--idx", str(idx),
        "--root", root,
        "--scratch", scratch,
        "--service-kwargs", json.dumps(service_kwargs),
    ]
    if incarnation:
        cmd += ["--incarnation", str(incarnation)]
    log_f = open(log_path, "ab")  # srlint: ckpt-ok child log sink, not persistent checkpoint state
    try:
        proc = subprocess.Popen(
            cmd, stdout=log_f, stderr=subprocess.STDOUT, env=env
        )
    finally:
        log_f.close()  # the child holds its own fd now
    directory = MemberDirectory(root)
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            rec = directory.lookup(member)
        except OSError:
            rec = None  # store outage: keep waiting inside the deadline
        if rec is not None and rec.get("pid") == proc.pid:
            return proc, str(rec["address"])
        if proc.poll() is not None:
            tail = ""
            try:
                with open(log_path, "r", errors="replace") as f:
                    tail = f.read()[-2000:]
            except OSError:
                pass
            # srlint: fault-ok boot-time spawn failure, before any replica exists for the chaos plane to target
            raise RuntimeError(
                f"replica {idx} subprocess exited during startup "
                f"(rc={proc.returncode}); log tail:\n{tail}"
            )
        if time.monotonic() > deadline:
            proc.kill()
            raise TimeoutError(
                f"replica {idx} subprocess published no member record "
                f"within {timeout_s:.0f}s (see {log_path})"
            )
        time.sleep(0.05)
