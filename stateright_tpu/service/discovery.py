"""Address discovery: member records in the fleet's shared store root.

Before this module, replica addresses were hand-wired — the spawner read a
``replica<i>.port`` file it had to share a local filesystem with, and a
replica that restarted on a new port was unreachable until someone
re-plumbed it. With a real multi-host root (an object store), the store
root itself is the only thing every process is guaranteed to share, so it
becomes the fleet's single shared configuration: each replica PUBLISHES a
``members/member-<name>.json`` record (address, pid, lease epoch,
heartbeat timestamp) into the root, and the router/spawner DISCOVERS and
re-discovers members from the root alone.

Records ride the ckptio CRC'd record seam (`write_record` /
`read_record_latest`) — crash-atomic with a ``.prev`` generation on both
backends, torn records skipped — and the listing rides the backend's
``blob.list`` chaos point, so a stale LIST degrades to yesterday's
membership view (re-discovery converges next round), never a wrong one.

Lifecycle contract:

- `publish` at boot, right after the HTTP server binds (the spawner waits
  for a record whose ``pid`` matches the child it just forked — a stale
  record from a previous incarnation can never satisfy a fresh spawn);
- `publish` again on a heartbeat cadence while the member's lease is
  still valid — a fenced zombie STOPS heartbeating, so its record goes
  stale instead of lying;
- a REJOINED member (fresh lease epoch, usually a fresh port) publishes a
  fresh record under the same member name: the router's `RemoteReplica`
  re-resolves the address from the record when its transport fails, which
  is what lets a restarted process re-enter the ring with zero re-wiring.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ..faults.blobstore import blob_backend, is_blob_uri, normalize_root
from ..faults.ckptio import read_record_latest, write_record

#: Member-record magic for the shared CRC'd record footer.
MEMBER_MAGIC = b"SRTPMBR1"


def _safe(member: str) -> str:
    return "".join(c if c.isalnum() or c in "-_@" else "_" for c in member)


class MemberDirectory:
    """The ``members/`` corner of a store root: publish / lookup / list
    member records. Stateless between calls — every reader re-reads the
    root, which is the whole point (discovery from the root alone)."""

    def __init__(self, root: str):
        self.root = normalize_root(root)
        self._dir = os.path.join(self.root, "members")
        # Member names THIS instance has published or resolved — the
        # read-your-own-writes floor under `members()`: a stale LIST
        # (the ``blob.list`` stale window) may omit a record we just
        # wrote, but it can never make this instance forget it. Names
        # only (records re-read per call — the listing stays the one
        # source of record truth; this is membership-of-the-listing).
        self._seen: set = set()

    def path_for(self, member: str) -> str:
        return os.path.join(self._dir, f"member-{_safe(member)}.json")

    def publish(
        self,
        member: str,
        address: str,
        pid: Optional[int] = None,
        epoch: int = 0,
    ) -> dict:
        """Write (or refresh — publishing IS the heartbeat) one member's
        record. Returns the record written."""
        if not is_blob_uri(self.root):
            os.makedirs(self._dir, exist_ok=True)
        rec = {
            "member": member,
            "address": address,
            "pid": int(pid if pid is not None else os.getpid()),
            "epoch": int(epoch),
            "ts": round(time.time(), 6),
        }
        write_record(
            self.path_for(member), json.dumps(rec).encode(), MEMBER_MAGIC
        )
        self._seen.add(member)
        return rec

    def lookup(self, member: str) -> Optional[dict]:
        """The member's newest intact record, or None (absent, torn, or
        the store is unreachable — discovery degrades to not-found, the
        caller retries on its own cadence)."""
        payload, _any = read_record_latest(
            self.path_for(member), MEMBER_MAGIC
        )
        if payload is None:
            return None
        try:
            rec = json.loads(payload)
        except ValueError:
            return None
        if isinstance(rec, dict) and "member" in rec:
            self._seen.add(member)
            return rec
        return None

    def members(self) -> list:
        """Every member with an intact record: the listing (the
        ``blob.list`` chaos surface — a stale listing is a stale
        membership view, converged by the next call) UNIONED with the
        names this instance already knows, so a member we just published
        or resolved is never hidden by the stale window —
        read-your-own-writes via the per-record `read_record_latest`
        path, which does not route through LIST."""
        names = set()
        for st in blob_backend(self._dir).list("member-"):
            if st.name.endswith(".prev"):
                continue
            names.add(st.name[len("member-"):].rsplit(".json", 1)[0])
        names.update(_safe(m) for m in self._seen)
        out = []
        for name in sorted(names):
            rec = self.lookup(name)
            if rec is not None:
                out.append(rec)
        return out

    def retire(self, member: str) -> None:
        """Best-effort record removal (clean shutdown); a crashed member's
        record simply goes stale instead."""
        self._seen.discard(member)
        self._seen.discard(_safe(member))
        path = self.path_for(member)
        try:
            if is_blob_uri(self.root):
                from ..faults.blobstore import delete_blob

                delete_blob(path)
                delete_blob(path + ".prev")
            else:
                for p in (path, path + ".prev"):
                    if os.path.exists(p):
                        os.unlink(p)
        except OSError:
            pass
