"""Epoch-fenced checkpoint leases: the cross-process fleet's zombie fence.

The r13 fleet's death story is sound only in-process: a hung-but-alive
replica declared dead by the router keeps stepping orphaned job copies,
and across a process boundary nothing stops it from still WRITING —
checkpoint generations, terminal journal events, corpus publishes — for
jobs the router already handed to a survivor. This module makes every such
false-positive death provably harmless, the way every lease-based
distributed store does (GFS/Chubby/Bigtable fencing tokens):

- The ROUTER owns one monotonically increasing epoch per ring member,
  persisted in a CRC-checked lease file under a shared directory
  (`LeaseStore`). `grant` bumps the member's epoch and marks it held;
  `revoke` — called BEFORE a dead member's jobs are requeued — marks it
  fenced. Both writes are crash-atomic (tmp+fsync+rename with a `.prev`
  generation, the faults/ckptio.py discipline).
- Every replica WRITE path re-validates its `Lease` at the write and
  stamps the write with (member, epoch): checkpoint generations through
  `ckptio.fenced_savez`, terminal/requeue-relevant journal events through
  `FencedEvents`, corpus publishes through `CorpusStore(lease=...)`.
  A revoked writer refuses its own write (`LeaseRevoked`) — and the one
  write that can slip past the check (in flight through an already-open
  fd when the revocation lands; the `fleet.zombie_write` chaos point
  simulates exactly this) is caught read-side: `ckptio.fenced_load_latest`
  and the corpus lookup reject any generation stamped with a revoked
  epoch, falling back to the newest validly-stamped one.
- Every refusal/rejection is COUNTED (`rejected_writes` / `rejected_reads`
  / `rejected_events`, exported through the obs REGISTRY "lease" source
  and summed into the fleet's `lease_rejected`): the acceptance currency
  for "the zombie wasted cycles but corrupted nothing".

Chaos points: ``lease.revoke_race`` fires at the top of `revoke` (an
injected fault leaves the lease granted; the router's death handling must
re-run it next tick), ``fleet.zombie_write`` is consumed by the fenced
writer (see ckptio).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from ..faults.blobstore import is_blob_uri, normalize_root
from ..faults.ckptio import (
    LeaseRevoked,
    fenced_load_latest,
    read_record_latest,
    write_record,
)
from ..faults.plan import maybe_fault
from ..obs import REGISTRY
from ..obs.schema import LEASE_GATED_EVENTS

#: Lease-record magic for the shared CRC'd record footer
#: (`ckptio.write_record` / `read_record_latest` — payload is JSON, not npz).
LEASE_MAGIC = b"SRTPLSE1"

GRANTED = "granted"
REVOKED = "revoked"


# Re-exported for API compatibility; the class itself lives in
# faults/ckptio.py so the store layer can catch it without importing the
# service layer.
__all__ = [
    "FencedEvents",
    "Lease",
    "LeaseRevoked",
    "LeaseStore",
    "load_fenced_resume",
]


class Lease:
    """One writer's fencing token: (member, epoch) plus the store to
    re-validate against. Handed to `ckptio.fenced_savez` (duck-typed:
    `.member` / `.epoch` / `.check()`), `FencedEvents`, and the corpus."""

    __slots__ = ("member", "epoch", "store")

    def __init__(self, member: str, epoch: int, store: "LeaseStore"):
        self.member = member
        self.epoch = epoch
        self.store = store

    def valid(self) -> bool:
        """Re-read the lease file: True iff this exact (member, epoch) is
        still granted. A torn/unreadable lease file reads as NOT valid —
        fencing fails safe (a fenced writer refuses; the router, the only
        lease writer, re-persists on its next transition)."""
        return self.store.validate(self.member, self.epoch)

    def check(self) -> None:
        """The write-side fence: raise `LeaseRevoked` (and count the
        refusal) instead of letting a revoked writer touch shared state."""
        if not self.valid():
            self.store.count_rejected("write")
            raise LeaseRevoked(
                f"lease for {self.member} (epoch {self.epoch}) is revoked; "
                "refusing the fenced write"
            )

    def __repr__(self) -> str:
        return f"Lease({self.member!r}, epoch={self.epoch})"


class LeaseStore:
    """The shared lease directory: one CRC-checked record per ring member,
    written only by the router (the single lease authority), read by every
    fenced writer/loader in every process. Thread-safe; counters exported
    through the obs REGISTRY "lease" source."""

    def __init__(self, root: str):
        self.root = normalize_root(root)
        if not is_blob_uri(self.root):
            os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.counters = {
            "grants": 0,
            "revokes": 0,
            "rejected_writes": 0,
            "rejected_reads": 0,
            "rejected_events": 0,
        }
        self._metrics_name = REGISTRY.register("lease", self.metrics)

    def path_for(self, member: str) -> str:
        # Member names are fleet-internal ("router", "replica0", ...);
        # sanitize anyway so a name can never escape the lease root.
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in member)
        return os.path.join(self.root, f"lease-{safe}.json")

    # -- the router's write side (single authority) ----------------------------

    def _write(self, member: str, epoch: int, state: str) -> None:
        """Crash-atomic lease record write through the ONE record seam
        (`ckptio.write_record`: in-memory payload + CRC footer +
        tmp/fsync/rename locally, a rotating conditional-safe PUT on the
        blob backend — previous record kept at `.prev` either way, so a
        torn current record falls back instead of bricking every fenced
        writer).

        VERIFIED after write: a lease transition that did not durably
        land is a broken fence, not a smaller one — a torn PUT of a
        REVOKE record would otherwise fall back to the still-granted
        `.prev` and quietly un-fence the zombie (found by the blob torn-
        put chaos). A failed verification retries the write (fresh blob
        generation); persistent failure raises, and the router's death
        handling aborts wholesale and re-runs next tick — revoke-before-
        requeue stays atomic."""
        payload = json.dumps(
            {"member": member, "epoch": int(epoch), "state": state}
        ).encode()
        path = self.path_for(member)
        for _attempt in range(3):
            write_record(path, payload, LEASE_MAGIC)
            if self._read(member) == (int(epoch), state):
                return
        # srlint: fault-ok the chaos boundary is the blob.put/ckpt record seam inside write_record; this raise IS the degrade path it feeds
        raise OSError(
            f"lease record for {member!r} failed post-write verification "
            "(torn writes exhausted retries); the transition is NOT durable"
        )

    def grant(self, member: str) -> Lease:
        """Grant `member` a fresh epoch (old epochs are implicitly revoked:
        validation requires an exact epoch match). Returns the Lease the
        holder stamps its writes with."""
        with self._lock:
            epoch, _state = self._read(member)
            epoch += 1
            self._write(member, epoch, GRANTED)
            self.counters["grants"] += 1
        return Lease(member, epoch, self)

    def revoke(self, member: str) -> Optional[int]:
        """Fence `member` out: persist its current epoch as revoked. MUST
        complete before the member's jobs are requeued (revoke-then-requeue
        is what makes the zombie's later writes provably stale). Idempotent;
        returns the revoked epoch (None when the member never held one).
        The ``lease.revoke_race`` chaos point fires BEFORE anything is
        persisted, so an injected fault leaves the lease granted and the
        caller simply retries on its next tick."""
        maybe_fault("lease.revoke_race", member=member)
        with self._lock:
            epoch, state = self._read(member)
            if epoch == 0:
                return None
            if state != REVOKED:
                self._write(member, epoch, REVOKED)
                self.counters["revokes"] += 1
            return epoch

    # -- everyone's read side --------------------------------------------------

    def _read(self, member: str) -> tuple:
        """(epoch, state) for `member`: the newest intact lease record,
        `.prev` fallback included; (0, "none") when the member never held
        a lease; (0, "unreadable") when every record is torn — or when
        the blob store is unreachable (fail-safe: validates False, so a
        fenced writer refuses during a store outage instead of guessing)."""
        payload, any_file = read_record_latest(
            self.path_for(member), LEASE_MAGIC
        )
        if payload is not None:
            try:
                rec = json.loads(payload)
                return int(rec["epoch"]), str(rec["state"])
            except (ValueError, KeyError):
                any_file = True
        return (0, "unreadable") if any_file else (0, "none")

    def state(self, member: str) -> tuple:
        return self._read(member)

    def validate(self, member: str, epoch: int) -> bool:
        """The fence predicate: (member, epoch) is valid iff the member's
        newest intact lease record says exactly this epoch is granted."""
        cur, state = self._read(member)
        return state == GRANTED and cur == int(epoch)

    def acquire(self, member: str) -> Lease:
        """A replica process picking up the lease the router granted it
        (the router grants BEFORE spawning; the holder only reads). Raises
        `LeaseRevoked` when no granted lease exists for `member`."""
        epoch, state = self._read(member)
        if state != GRANTED or epoch == 0:
            raise LeaseRevoked(
                f"no granted lease for {member!r} (state={state}, "
                f"epoch={epoch}); the router grants before spawn"
            )
        return Lease(member, epoch, self)

    # -- accounting ------------------------------------------------------------

    def count_rejected(self, surface: str, n: int = 1) -> None:
        """Account one fenced refusal/rejection: `surface` is "write"
        (pre-write check refused), "read" (a loader skipped a
        stale-stamped generation), or "event" (FencedEvents dropped a
        gated journal event)."""
        key = f"rejected_{surface}s"
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def rejected_total(self) -> int:
        with self._lock:
            return sum(
                v for k, v in self.counters.items()
                if k.startswith("rejected_")
            )

    def metrics(self) -> dict:
        """Flat counters for the obs REGISTRY "lease" source."""
        with self._lock:
            out = dict(self.counters)
        out["rejected_total"] = sum(
            v for k, v in out.items() if k.startswith("rejected_")
        )
        return out

    def close(self) -> None:
        REGISTRY.unregister(self._metrics_name)


def load_fenced_resume(path: str, lease_store: Optional[LeaseStore]):
    """ResumeToken path -> queue.JobResume through the fence, or None
    (restart fresh — still exact) when nothing loadable survives CRC +
    stamp validation. THE one spelling of replica-side resume resolution
    (in-proc Replica, the remote serve_replica, tools): rejected
    generations are counted as lease "read" rejections; every other
    failure mode degrades to a fresh restart."""
    from .queue import JobResume

    try:
        data, _src = fenced_load_latest(
            path,
            validator=(
                lease_store.validate if lease_store is not None else None
            ),
            on_reject=(
                (lambda _p, _m, _e: lease_store.count_rejected("read"))
                if lease_store is not None else None
            ),
        )
    except Exception:  # noqa: BLE001 — any unreadable generation: fresh
        return None
    return JobResume.from_npz(data)


class FencedEvents:
    """Flight-recorder wrapper that gates terminal/requeue-relevant journal
    events behind the writer's lease (obs/schema.py LEASE_GATED_EVENTS) and
    stamps every event with the writer's epoch. A revoked writer's gated
    emit is dropped, counted, and recorded as a `lease.reject` event
    (rejection is evidence — it is itself ungated). Hot-path events
    (engine.chunk) pass through unchecked: gating them would put lease-file
    I/O on the fused-step path, and the timeline treats them as harmless.
    """

    def __init__(self, events, lease: Lease):
        self._inner = events
        self._lease = lease

    # The journal surface call sites rely on (obs/events.py):

    @property
    def enabled(self) -> bool:
        return self._inner.enabled

    @property
    def closed(self) -> bool:
        return self._inner.closed

    @property
    def writer(self):
        return self._inner.writer

    @property
    def path(self):
        return self._inner.path

    def emit(self, etype: str, **fields):
        if etype in LEASE_GATED_EVENTS and not self._lease.valid():
            self._lease.store.count_rejected("event")
            try:
                self._inner.emit(
                    "lease.reject", member=self._lease.member,
                    epoch=self._lease.epoch, surface="event", dropped=etype,
                )
            except Exception:  # noqa: BLE001 — recording never raises upward
                pass
            return None
        fields.setdefault("epoch", self._lease.epoch)
        return self._inner.emit(etype, **fields)

    def flush(self) -> None:
        self._inner.flush()

    def close(self) -> None:
        self._inner.close()

    def tail(self, since: int = 0, job=None, wait_s: float = 0.0) -> tuple:
        return self._inner.tail(since=since, job=job, wait_s=wait_s)

    def recent(self, n: int = 16) -> list:
        return self._inner.recent(n)

    def cursor(self) -> int:
        return self._inner.cursor()
