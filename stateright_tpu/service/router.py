"""Fleet front door: consistent-hash routing, health, requeue, stealing.

The r8 CheckService multiplexes every job onto ONE device — a single
replica crash is the whole fleet crashing. `FleetRouter` is the production
layer above it (ROADMAP item 1): N CheckService replicas (service/fleet.py
wraps each in a `Replica` driver) behind one submission surface that
survives replica death with zero lost jobs.

Routing policy:

- **Consistent hashing** (`HashRing`): jobs are placed by a stable route
  key — by default the model's registry/type name, so same-model jobs land
  on the same replica and share its compiled step and batch lanes (the
  cache-affinity argument for consistent hashing, which is also the
  continuous-batching win). When a replica dies, only ITS keys move; every
  other job keeps its warm replica.
- **Bounded retry with deterministic backoff**: a submission that times out
  or faults (`router.timeout` on the chaos plane) is retried against the
  ring's successor replicas, with the same seeded-jitter backoff the
  supervisor uses — replayable run to run.
- **Health probes**: the router probes each replica's status surface on a
  cadence (the `/.status` plane, in-proc); `unhealthy_after` consecutive
  probe failures — or a dead driver — declares the replica crashed.
- **Failure → requeue-resume**: a dead replica's unfinished jobs are
  requeued onto ring survivors. When the replica's driver checkpointed the
  job (faults/ckptio.py atomic generations), `load_latest` restores the
  newest intact one and the job RESUMES mid-search (queue.JobResume seeds
  the survivor's table from the journal) instead of restarting; with no
  intact generation the job restarts fresh — either way BFS determinism
  keeps results bit-identical, and either way the job is never lost.
- **Work stealing** (`fleet.steal`): an idle replica pulls still-QUEUED
  jobs from the most-loaded replica's admission queue (the TPU analogue of
  the reference's `job_market.rs` thread stealing — a queued job has no
  table state, so the move is a clean withdraw-here/submit-there).

`serve_fleet` is the HTTP front door (`POST /jobs`, `GET /jobs/<id>`,
cancel, `GET /jobs/<id>/events` flight-recorder long-poll, fleet-level
`/.status` + Prometheus `/metrics` aggregating every replica through the
obs registry). Overload and injected `service.http` faults degrade to
503 + `Retry-After` — clients back off, never hot-loop.

Every routing decision is also journaled (obs/events.py, wired by
`ServiceFleet(journal_dir=...)`): submissions mint a job-scoped trace id
here and carry it through every replica hop, so `job.submitted` →
`router.route` → `replica.admit` → crash → `job.requeued` →
`job.resumed` → `job.done` reads as ONE timeline in the forensic CLI
(`python -m stateright_tpu.obs.timeline`).
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Optional

from ..core.discovery import HasDiscoveries
from ..faults.ckptio import (
    CheckpointCorrupt,
    LEASE_STAMP_KEYS,
    fenced_load_latest,
    fenced_savez,
    latest_generation,
)
from ..faults.plan import (
    FaultError,
    _u01,
    active_plan,
    deterministic_backoff,
    maybe_fault,
)
from ..obs import (
    REGISTRY,
    TERMINAL_EVENT_BY_STATUS,
    as_events,
    as_tracer,
    mint_trace_id,
)
from .queue import JobStatus
from .tenancy import DEFAULT_TENANT, QuotaExceeded


class ReplicaDead(RuntimeError):
    """The targeted replica's driver has stopped (crash, hang past the
    probe policy, or shutdown); the router must place the work elsewhere."""


def lease_member(idx: int) -> str:
    """The ONE spelling of a replica's lease-member / journal-writer name
    (fleet wiring, replica_main, and the timeline fence all key on it)."""
    return f"replica{idx}"


class ResumeToken:
    """A requeued/stolen job's resume pointer: the checkpoint path whose
    newest FENCED generation the next replica must resume from. The token
    (not a loaded payload) crosses the replica seam so each replica kind
    resolves it where the bytes are cheap: an in-proc `Replica` loads it
    in this process, a `RemoteReplica` sends the path over HTTP and the
    serving process loads it against the shared store root — both through
    `ckptio.fenced_load_latest`, so a zombie's stale generation is
    rejected wherever the resume happens."""

    __slots__ = ("path",)

    def __init__(self, path: str):
        self.path = path

    def __repr__(self) -> str:
        return f"ResumeToken({self.path!r})"


class NoHealthyReplica(RuntimeError):
    """Every replica is dead; rendered as a 503 + Retry-After over HTTP."""


class FleetJobStatus:
    ROUTED = "routed"  # bound to a replica (queued or running there)
    DONE = "done"
    CANCELLED = "cancelled"
    ERROR = "error"

    FINISHED = (DONE, CANCELLED, ERROR)


# -- consistent hashing --------------------------------------------------------


class HashRing:
    """crc32 consistent-hash ring with virtual nodes. `lookup(key)` is the
    owner; `preference(key)` is the owner followed by distinct successors —
    the retry/failover order. Removing a member moves ONLY its keys."""

    def __init__(self, members, vnodes: int = 64):
        self.vnodes = vnodes
        self._points: list = []  # sorted [(hash, member)]
        self._members: set = set()
        for m in members:
            self.add(m)

    @staticmethod
    def _hash(s: str) -> int:
        return zlib.crc32(s.encode()) & 0xFFFFFFFF

    def add(self, member) -> None:
        if member in self._members:
            return
        self._members.add(member)
        # Rebind (never mutate in place): concurrent readers snapshot
        # self._points once and must never observe a mid-sort list.
        self._points = sorted(
            self._points
            + [(self._hash(f"{member}#{v}"), member) for v in range(self.vnodes)]
        )

    def remove(self, member) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        self._points = [(h, m) for h, m in self._points if m != member]

    def members(self) -> list:
        return sorted(self._members)

    def lookup(self, key: str):
        order = self.preference(key)
        return order[0] if order else None

    def preference(self, key: str) -> list:
        """Every member, ordered by ring distance from `key`'s point —
        index 0 is the owner, the rest are the failover walk."""
        points = self._points  # one snapshot: remove() may rebind mid-walk
        if not points:
            return []
        h = self._hash(key)
        # First point at or after h (wrap), then walk clockwise.
        lo, hi = 0, len(points)
        while lo < hi:
            mid = (lo + hi) // 2
            if points[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        seen: list = []
        n = len(points)
        for i in range(n):
            m = points[(lo + i) % n][1]
            if m not in seen:
                seen.append(m)
        return seen


# -- fleet jobs ----------------------------------------------------------------


class FleetJob:
    """Router-side record of one submitted job: the spec (enough to
    resubmit it anywhere), its current binding, and its completion state."""

    def __init__(self, fleet_id: int, model, key: str, opts: dict,
                 ckpt_path: Optional[str], model_ref: Optional[tuple] = None):
        self.id = fleet_id
        self.model = model
        # (registry name, args dict) when known — what a REMOTE replica
        # submits across the process boundary (model objects cannot cross
        # it; both sides resolve the ref through the same ModelRegistry).
        self.model_ref = model_ref
        self.key = key
        # Flight-recorder trace id: minted HERE (the outermost front door)
        # and carried through every replica the job ever touches, so the
        # timeline CLI reads the whole hop story as one lifecycle.
        self.trace = mint_trace_id()
        self.opts = opts  # finish_when/targets/timeout/priority
        self.ckpt_path = ckpt_path
        self.status = FleetJobStatus.ROUTED
        self.replica: Optional[int] = None
        self.handle = None  # inner JobHandle on the bound replica
        self.requeues = 0
        self.steals = 0
        self.result = None
        self.error: Optional[str] = None
        self.event = threading.Event()
        self.submitted_at = time.monotonic()
        self.finished_at: Optional[float] = None


class FleetJobHandle:
    """Client-side handle (the fleet twin of api.JobHandle). The handle
    survives requeues and steals — it tracks the job, not a replica."""

    def __init__(self, router: "FleetRouter", job: FleetJob):
        self._router = router
        self._job = job

    @property
    def id(self) -> int:
        return self._job.id

    def status(self) -> str:
        return self._job.status

    def poll(self) -> dict:
        return self._router.poll(self._job.id)

    def result(self, wait: bool = True, timeout: Optional[float] = None):
        return self._router.result(self._job.id, wait=wait, timeout=timeout)

    def cancel(self) -> bool:
        return self._router.cancel(self._job.id)

    def discoveries(self) -> dict:
        return self._router.discovery_paths(self._job.id)


# -- the router ----------------------------------------------------------------


class FleetRouter:
    def __init__(
        self,
        replicas,
        seed: int = 0,
        retry_limit: int = 2,
        backoff_base_s: float = 0.02,
        backoff_cap_s: float = 0.5,
        probe_timeout_s: float = 1.0,
        unhealthy_after: int = 3,
        steal: bool = True,
        background: bool = False,
        ckpt_dir: Optional[str] = None,
        tracer=None,
        events=None,
        lease_store=None,
        router_lease=None,
        probe_backoff_base: int = 1,
        probe_backoff_cap: int = 8,
        probation_probes: int = 2,
        quotas=None,
    ):
        """`replicas` are service/fleet.py `Replica` drivers (one
        CheckService each). `background=True` makes probes run under a
        deadline thread (a hung replica must not hang the router);
        foreground mode (deterministic tests) probes inline. `ckpt_dir`
        enables the requeue-resume plane (per-job checkpoint generations
        written by the replica drivers, restored here on replica death).
        `events` is the router's flight-recorder journal (obs/events.py,
        usually `ServiceFleet(journal_dir=...)`'s `router.jsonl`): every
        routing decision, failover, requeue, and steal is journaled keyed
        by the job's trace id, the fleet `/.status` carries the last-N
        event ring, and `GET /jobs/<id>/events` tails it live.

        `lease_store` + `router_lease` (service/lease.py, wired by
        `ServiceFleet(lease_dir=...)` / remote mode) turn on epoch
        fencing: this router is the single lease authority — it REVOKES a
        member's lease before requeueing its jobs (so the member's later
        writes are provably stale) and re-seals each orphan's newest
        intact checkpoint generation under its own never-revoked lease.

        `probe_backoff_base` / `probe_backoff_cap` (ticks) are the
        exponential probe backoff for repeatedly-failing members: a
        partitioned replica's probes are deferred (with seeded jitter)
        instead of eating the tick budget every round.

        `probation_probes` is the rejoin quarantine: a dead/fenced member
        re-registered through `rejoin` must answer this many CONSECUTIVE
        health probes before its keys move back (`HashRing.add` — only
        ITS keys, mirroring dead-member removal); until promotion it
        receives no placements and neither steals nor is stolen from.

        `quotas` (service/tenancy.py `TenantQuotas`) turns on the
        fleet-wide admission gate: `submit(tenant=...)` counts the
        tenant's unfinished fleet jobs against its `max_in_flight` and
        its lane-seconds spend against its windowed budget, rejecting
        with `QuotaExceeded` (rendered as HTTP 429 + Retry-After by
        serve_fleet). The default tenant is never gated."""
        self.replicas = {r.idx: r for r in replicas}
        self.ckpt_dir = ckpt_dir
        self.ring = HashRing(list(self.replicas))
        self.seed = seed
        self.retry_limit = retry_limit
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.probe_timeout_s = probe_timeout_s
        self.unhealthy_after = unhealthy_after
        self.steal = steal
        self.background = background
        self._tracer = as_tracer(tracer)
        self._events = as_events(events)
        self.lease_store = lease_store
        self.router_lease = router_lease
        self.quotas = quotas
        self.probe_backoff_base = max(int(probe_backoff_base), 1)
        self.probe_backoff_cap = max(int(probe_backoff_cap), 1)
        self._jobs: dict[int, FleetJob] = {}
        self._next_id = 1
        self._lock = threading.RLock()
        self.probation_probes = max(int(probation_probes), 1)
        self._suspect: dict[int, int] = {r: 0 for r in self.replicas}
        self._dead: set = set()
        self._tick_n = 0
        self._next_probe: dict[int, int] = {}  # idx -> earliest probe tick
        self._probation: dict[int, int] = {}  # idx -> healthy probes still owed
        # Members mid-retirement (autoscale scale-in drain): excluded from
        # placement AND from stealing (as thieves — their backlog is still
        # fair game for others to steal away, which IS the drain).
        self._draining: set = set()
        self.counters = {
            "jobs_routed": 0,
            "router_retries": 0,
            "router_backoff_ms": 0,
            "probe_failures": 0,
            "probe_skipped": 0,
            "replica_crashes": 0,
            "requeued_jobs": 0,
            "restored_jobs": 0,
            "steals": 0,
            "lease_revokes": 0,
            "lease_reseals": 0,
            "rejoins": 0,
            "rejoin_promotions": 0,
            "quota_rejected": 0,
            "scale_outs": 0,
            "scale_ins": 0,
        }
        self._metrics_name = REGISTRY.register("fleet", self.metrics)
        if self.lease_store is not None:
            # The grants happened before the replicas started (a remote
            # member ACQUIRES its lease at boot); journal them here so the
            # lease lifecycle reads start-to-finish in the router journal.
            for idx in self.replicas:
                epoch, _state = self.lease_store.state(lease_member(idx))
                if epoch:
                    self._events.emit(
                        "lease.grant", member=lease_member(idx), epoch=epoch
                    )

    # -- client surface --------------------------------------------------------

    def submit(
        self,
        model,
        route_key: Optional[str] = None,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        target_state_count: Optional[int] = None,
        target_max_depth: Optional[int] = None,
        timeout: Optional[float] = None,
        priority: int = 0,
        model_ref: Optional[tuple] = None,
        tenant: str = DEFAULT_TENANT,
    ) -> FleetJobHandle:
        """Route one job onto the fleet; returns immediately. `route_key`
        defaults to the model's type name — same-key jobs share a replica
        (and so a compiled step); distinct keys spread over the ring.
        `model_ref=(registry name, args)` is REQUIRED when any replica is
        remote: model objects cannot cross the process boundary, so the
        ref is what a RemoteReplica submits (both sides resolve it through
        the same ModelRegistry; serve_fleet fills it in automatically).
        `tenant` is the job's billing identity: it rides in `opts` through
        `_spec` into every replica's `CheckService.submit` (quota
        accounting, tenant-fair admission, tenant-salted corpus keys all
        key on it), and when the router was built with `quotas` a
        non-default tenant over its in-flight or lane-seconds budget is
        rejected HERE with `QuotaExceeded` before any replica is
        touched."""
        if self.quotas is not None and tenant != DEFAULT_TENANT:
            with self._lock:
                in_flight = sum(
                    1 for fj in self._jobs.values()
                    if fj.opts.get("tenant") == tenant
                    and fj.status not in FleetJobStatus.FINISHED
                )
            try:
                self.quotas.admit(tenant, in_flight)
            except QuotaExceeded:
                with self._lock:
                    self.counters["quota_rejected"] += 1
                self._events.emit("job.quota_rejected", tenant=tenant)
                raise
        if not self._healthy():
            # One of the satellite 503/Retry-After surfaces: journaled so
            # a forensic pass can see WHY clients were bounced.
            self._events.emit(
                "router.unavailable", reason="no healthy replica"
            )
            self._tracer.instant("router.unavailable", cat="fleet")
            raise NoHealthyReplica(
                "every fleet replica is dead; resubmit after recovery"
            )
        if model_ref is None and any(
            getattr(r, "remote", False) for r in self.replicas.values()
        ):
            # Caller-contract violation, not a fleet failure: without the
            # early check, every placement attempt would misread the
            # refusal as ReplicaDead and burn the failover walk on
            # perfectly healthy replicas.
            raise TypeError(
                "this fleet has remote replicas: submit() needs "
                "model_ref=(registry name, args) — model objects cannot "
                "cross the process boundary"
            )
        key = route_key if route_key is not None else type(model).__name__
        opts = dict(
            finish_when=finish_when,
            target_state_count=target_state_count,
            target_max_depth=target_max_depth,
            timeout=timeout,
            priority=priority,
            tenant=tenant,
        )
        with self._lock:
            fj = FleetJob(
                self._next_id, model, key, opts,
                self._ckpt_path_for(self._next_id), model_ref=model_ref,
            )
            self._next_id += 1
            self._jobs[fj.id] = fj
        self._events.emit(
            "job.submitted", job=fj.id, trace=fj.trace, key=key
        )
        self._place(fj)
        return FleetJobHandle(self, fj)

    def _ckpt_path_for(self, fleet_id: int) -> Optional[str]:
        if self.ckpt_dir is None:
            return None
        import os

        return os.path.join(self.ckpt_dir, f"fleetjob{fleet_id}.npz")

    def poll(self, job_id: int) -> dict:
        fj = self._get(job_id)
        with self._lock:
            out = {
                "id": fj.id,
                "status": fj.status,
                "trace": fj.trace,
                "replica": fj.replica,
                "requeues": fj.requeues,
                "steals": fj.steals,
                "error": fj.error,
            }
            if fj.handle is not None:
                try:
                    inner = fj.handle.poll()
                except Exception:  # noqa: BLE001 — a dead replica's poll
                    inner = None
                if inner is not None:
                    for k in (
                        "state_count", "unique_state_count", "max_depth",
                        "discoveries",
                    ):
                        out[k] = inner.get(k)
                    out["replica_status"] = inner.get("status")
            return out

    def result(
        self, job_id: int, wait: bool = True, timeout: Optional[float] = None
    ):
        fj = self._get(job_id)
        if wait:
            if not fj.event.wait(timeout):
                raise TimeoutError(f"fleet job {job_id} still running")
        elif not fj.event.is_set():
            return None
        if fj.status == FleetJobStatus.CANCELLED:
            # srlint: fault-ok caller-contract guard (cancellation is the caller's own act)
            raise RuntimeError(f"fleet job {job_id} was cancelled")
        if fj.status == FleetJobStatus.ERROR:
            # srlint: fault-ok re-raising a job failure the fleet already absorbed
            raise RuntimeError(fj.error or f"fleet job {job_id} failed")
        return fj.result

    def cancel(self, job_id: int) -> bool:
        fj = self._get(job_id)
        with self._lock:
            if fj.status in FleetJobStatus.FINISHED:
                return False
            if fj.handle is not None:
                try:
                    fj.handle.cancel()
                except Exception:  # noqa: BLE001 — dead replica: job dies here
                    pass
            self._finish(fj, FleetJobStatus.CANCELLED)
            return True

    def discovery_paths(self, job_id: int) -> dict:
        fj = self._get(job_id)
        if fj.handle is None:
            return {}
        return fj.handle.discoveries()

    def job_ids(self) -> list:
        with self._lock:
            return sorted(self._jobs)

    def _get(self, job_id: int) -> FleetJob:
        with self._lock:
            try:
                return self._jobs[job_id]
            except KeyError:
                raise KeyError(f"no such fleet job {job_id}") from None

    # -- placement -------------------------------------------------------------

    def _healthy(self) -> list:
        return [
            r for r in self.replicas.values()
            if r.idx not in self._dead and r.alive
        ]

    def _spec(self, fj: FleetJob, resume=None) -> dict:
        return dict(
            fj.opts,
            model=fj.model,
            model_ref=fj.model_ref,
            journal=fj.ckpt_path is not None,
            resume=resume,  # None | ResumeToken (each replica resolves it)
            trace=fj.trace,  # one timeline across every replica hop
        )

    def _backoff(self, attempt: int) -> None:
        # The ONE seeded backoff spelling (faults/plan.py), shared with
        # the supervisor's retry slices and the blob-store client.
        delay = deterministic_backoff(
            self.seed, "router.backoff", attempt,
            self.backoff_base_s, self.backoff_cap_s,
        )
        if delay <= 0:
            return
        with self._lock:
            self.counters["router_backoff_ms"] += int(delay * 1000)
        time.sleep(delay)

    def _place(self, fj: FleetJob, resume=None) -> bool:
        """Bind `fj` to a replica along its ring preference, retrying
        faults with deterministic backoff. On exhaustion the job is failed
        (never silently dropped). The whole walk is one `router.place`
        span; every failed attempt is a `router.failover` journal event,
        every binding a `router.route`."""
        last: Optional[BaseException] = None
        with self._tracer.span(
            "router.place", cat="fleet", job=fj.id, trace=fj.trace,
            resumed=resume is not None,
        ):
            for attempt in range(self.retry_limit + 1):
                order = [
                    i for i in self.ring.preference(fj.key)
                    if i not in self._dead and i not in self._draining
                    and self.replicas[i].alive
                ]
                if not order:
                    # Ring empty but probation members alive (every live
                    # member is mid-rejoin — e.g. the 1-replica fleet's
                    # only member rejoining): place on them rather than
                    # hard-failing the job. "No placements during
                    # probation" is a load-shedding policy for when ring
                    # members exist, not a reason to turn a 2-tick
                    # quarantine window into a permanent job ERROR.
                    with self._lock:
                        order = sorted(
                            i for i in self._probation
                            if i not in self._dead
                            and i not in self._draining
                            and self.replicas[i].alive
                        )
                if not order:
                    break
                r = self.replicas[order[attempt % len(order)]]
                try:
                    # Chaos-plane boundary: an injected `router.timeout`
                    # fires BEFORE the replica is touched, so the retry is
                    # exact.
                    maybe_fault("router.timeout", replica=r.idx, job=fj.id)
                    handle = r.submit(self._spec(fj, resume), fj.ckpt_path)
                except (FaultError, ReplicaDead) as e:
                    last = e
                    with self._lock:
                        self.counters["router_retries"] += 1
                    self._tracer.instant(
                        "router.failover", cat="fleet", job=fj.id,
                        replica=r.idx, trace=fj.trace,
                    )
                    self._events.emit(
                        "router.failover", job=fj.id, trace=fj.trace,
                        replica=r.idx, error=type(e).__name__,
                    )
                    self._backoff(attempt)
                    continue
                with self._lock:
                    if fj.status in FleetJobStatus.FINISHED:
                        # A cancel raced the (re)placement: reap the copy.
                        try:
                            handle.cancel()
                        except Exception:  # noqa: BLE001 — best-effort reap
                            pass
                        return False
                    if r.idx in self._dead or not r.alive:
                        # The replica died between submit and bind: binding
                        # now would park the job on a corpse forever (the
                        # death handler already scanned for orphans and
                        # missed this still-unbound job). Treat it as a
                        # failed attempt.
                        last = ReplicaDead(
                            f"replica {r.idx} died during placement"
                        )
                        continue
                    fj.replica = r.idx
                    fj.handle = handle
                    self.counters["jobs_routed"] += 1
                self._events.emit(
                    "router.route", job=fj.id, trace=fj.trace,
                    replica=r.idx, resumed=bool(resume) or None,
                    attempt=attempt or None,
                )
                return True
        with self._lock:
            if fj.status in FleetJobStatus.FINISHED:
                return False  # cancelled while no replica would take it
            fj.error = (
                f"no healthy replica accepted fleet job {fj.id}"
                + (f" (last: {type(last).__name__}: {last})" if last else "")
            )
            self._finish(fj, FleetJobStatus.ERROR)
        return False

    def _finish(self, fj: FleetJob, status: str) -> None:
        fj.status = status
        fj.finished_at = time.monotonic()
        # Every fleet job's timeline ends with exactly one router-side
        # terminal event (the timeline CLI's no_terminal anomaly guard).
        self._events.emit(
            TERMINAL_EVENT_BY_STATUS[status], job=fj.id, trace=fj.trace,
            error=fj.error, requeues=fj.requeues or None,
            steals=fj.steals or None,
        )
        fj.event.set()

    # -- replica rejoin --------------------------------------------------------

    def rejoin(self, replica) -> bool:
        """Re-admit a restarted incarnation of a dead/fenced member behind
        the quarantine policy: the new driver replaces the dead one in the
        replica map and is probed like any member, but its keys do NOT
        move back until it answers `probation_probes` consecutive health
        probes — only then does `HashRing.add` re-route ITS keys (and only
        its keys: consistent hashing makes re-add the exact mirror of
        dead-member removal, pinned by the ring unit tests). The caller
        (ServiceFleet.rejoin_replica) granted the member a FRESH lease
        epoch first, so a stale zombie of the old incarnation racing this
        rejoin is fence-rejected on every write — the exact-epoch check
        fails for the old epoch the moment the grant lands.

        The ``fleet.rejoin`` chaos point fires at the TOP of the caller
        (`ServiceFleet.rejoin_replica`) — before the fresh grant, before
        the spawn — so an injected fault aborts the whole rejoin with
        literally nothing changed (not even a burned epoch).

        A BRAND-NEW index (autoscale scale-out, `ServiceFleet.scale_out`)
        joins through the same door and the same quarantine: it is
        registered and probed like any member but gets no keys (and no
        placements) until `probation_probes` consecutive healthy probes
        promote it — a flapping new member never receives work it would
        immediately orphan. The only differences are the books: the join
        counts as `scale_outs` (not `rejoins`) and journals
        `fleet.scale_out` (not `replica.rejoin`)."""
        with self._lock:
            grown = replica.idx not in self.replicas
            if not grown and replica.idx not in self._dead:
                return False  # alive: nothing to rejoin
            self._dead.discard(replica.idx)
            self._draining.discard(replica.idx)
            self.replicas[replica.idx] = replica
            self._suspect[replica.idx] = 0
            self._next_probe.pop(replica.idx, None)
            self._probation[replica.idx] = self.probation_probes
            if grown:
                self.counters["scale_outs"] += 1
            else:
                self.counters["rejoins"] += 1
        if grown:
            self._tracer.instant(
                "fleet.scale_out", cat="fleet", replica=replica.idx
            )
            self._events.emit(
                "fleet.scale_out", replica=replica.idx,
                probes=self.probation_probes,
            )
            return True
        self._tracer.instant(
            "fleet.rejoin", cat="fleet", replica=replica.idx
        )
        self._events.emit(
            "replica.rejoin", replica=replica.idx, phase="probation",
            probes=self.probation_probes,
        )
        return True

    def _promote(self, idx: int) -> None:
        """Probation served: move the member's keys back (ring re-add)."""
        with self._lock:
            if self._probation.pop(idx, None) is None:
                return
            self.counters["rejoin_promotions"] += 1
        self.ring.add(idx)
        self._events.emit(
            "replica.rejoin", replica=idx, phase="ring"
        )
        self._tracer.instant(
            "fleet.rejoin_promoted", cat="fleet", replica=idx
        )

    # -- replica retire (autoscale scale-in) -----------------------------------

    def retire(self, idx: int) -> bool:
        """Gracefully remove a HEALTHY member (autoscale scale-in,
        `ServiceFleet.scale_in`). The drain is loss-free by the same
        argument as the death path, in a safer order:

        1. mark the member DRAINING — no new placements land on it and it
           stops stealing (its own queue stays stealable: steals away from
           it are the drain working);
        2. revoke its lease (persisted) — from here every write the
           still-running member attempts is provably stale, exactly the
           zombie discipline of `_on_replica_death`. A revocation that
           does not durably land aborts the WHOLE retirement (the member
           un-drains and keeps serving; the autoscaler retries next tick);
        3. remove it from the ring, requeue every unfinished job it held
           onto survivors — resumed from the newest intact re-sealed
           checkpoint generation when one exists, restarted fresh
           otherwise. BFS determinism keeps results bit-identical either
           way (the scale-in drain golden test pins this).

        Journals ONE `fleet.scale_in` (and counts `scale_ins`), not
        `replica.crash` — the timeline must read as a decision, not a
        failure. Refuses (False) to retire the last healthy member.
        The ``fleet.autoscale`` chaos point fires in the CALLER before
        anything here runs, so an injected fault changes nothing."""
        with self._lock:
            r = self.replicas.get(idx)
            if r is None or idx in self._dead:
                return False
            survivors = [
                i for i in self.replicas
                if i != idx and i not in self._dead
                and i not in self._draining and self.replicas[i].alive
            ]
            if not survivors:
                return False  # never drain the fleet to zero members
            self._draining.add(idx)
        member = lease_member(idx)
        if self.lease_store is not None:
            try:
                epoch = self.lease_store.revoke(member)
            except (FaultError, OSError):
                # The revocation did not durably land: abort the whole
                # retirement — requeueing before a durable revoke would
                # hand the still-running member a license to corrupt.
                with self._lock:
                    self._draining.discard(idx)
                self._tracer.instant(
                    "lease.revoke_race", cat="fleet", member=member
                )
                return False
            if epoch is not None:
                self.counters["lease_revokes"] += 1
                self._events.emit(
                    "lease.revoke", member=member, epoch=epoch
                )
        with self._lock:
            self._dead.add(idx)
            self._draining.discard(idx)
            self._probation.pop(idx, None)
            orphans = [
                fj for fj in self._jobs.values()
                if fj.replica == idx
                and fj.status not in FleetJobStatus.FINISHED
            ]
            self.counters["scale_ins"] += 1
        self.ring.remove(idx)
        self._tracer.instant(
            "fleet.scale_in", cat="fleet", replica=idx,
            orphans=len(orphans),
        )
        self._events.emit(
            "fleet.scale_in", replica=idx, orphans=len(orphans)
        )
        with self._tracer.span(
            "fleet.drain", cat="fleet", replica=idx, orphans=len(orphans)
        ):
            for fj in orphans:
                with self._lock:
                    fj.requeues += 1
                    fj.replica = None
                    fj.handle = None
                    self.counters["requeued_jobs"] += 1
                resume = self._resume_token(fj, reseal=True)
                if resume is not None:
                    self.counters["restored_jobs"] += 1
                self._events.emit(
                    "job.requeued", job=fj.id, trace=fj.trace, src=idx,
                    reason="scale-in drain", restored=resume is not None,
                )
                self._place(fj, resume=resume)
        return True

    # -- supervision tick ------------------------------------------------------

    def tick(self) -> None:
        """One supervision round: probe health (dead → requeue), harvest
        finished inner jobs, steal for idle replicas. Driven by the fleet's
        router thread (background) or `ServiceFleet.pump` (foreground)."""
        plan = active_plan()
        if (
            plan is not None
            and self._events.enabled
            and (plan.events is None or plan.events.closed)
        ):
            # Flight-recorder adoption of the active chaos plan: every
            # injection anywhere in the process lands in this journal as
            # `fault.injected` — a chaos run is an auditable recording.
            # A closed adoptee (a previous run's journal) is replaced.
            plan.events = self._events
        self._probe_all()
        self._harvest()
        if self.steal:
            self._steal()

    def _probe_all(self) -> None:
        self._tick_n += 1
        for r in list(self.replicas.values()):
            if r.idx in self._dead:
                continue
            if not r.alive:
                self._on_replica_death(r)
                continue
            if self._tick_n < self._next_probe.get(r.idx, 0):
                # Exponential probe backoff: a repeatedly-failing member
                # (partitioned, hung) is probed on a widening jittered
                # cadence instead of eating a probe deadline out of EVERY
                # router tick.
                self.counters["probe_skipped"] += 1
                continue
            ok = self._probe(r)
            if ok is None:
                # The probe worker never got scheduled inside the budget
                # (host starvation — e.g. compile threads hogging a small
                # box): that measures the HOST, not the replica. No
                # evidence either way — neither reset nor grow suspicion.
                continue
            if ok:
                self._suspect[r.idx] = 0
                self._next_probe.pop(r.idx, None)
                owed = self._probation.get(r.idx)
                if owed is not None:
                    # One healthy probation probe served; promotion (ring
                    # re-add) happens only when the full run is CONSECUTIVE.
                    if owed <= 1:
                        self._promote(r.idx)
                    else:
                        self._probation[r.idx] = owed - 1
                continue
            if r.idx in self._probation:
                # A failed probe resets the probation clock: the quarantine
                # demands consecutive health, not eventual health.
                self._probation[r.idx] = self.probation_probes
            self.counters["probe_failures"] += 1
            self._suspect[r.idx] += 1
            backoff = min(
                self.probe_backoff_base * 2 ** (self._suspect[r.idx] - 1),
                self.probe_backoff_cap,
            )
            # Seeded jitter (±25%): N members suspected on the same tick
            # must not re-probe in lockstep forever.
            jitter = 0.75 + 0.5 * _u01(
                self.seed, "router.probe_jitter",
                self._tick_n * 131 + r.idx,
            )
            self._next_probe[r.idx] = self._tick_n + max(
                1, int(round(backoff * jitter))
            )
            # Journal/span only probe FAILURES: healthy probes fire every
            # tick per replica and would drown both planes in no-ops —
            # the suspect counter is the forensic story a failure tells.
            self._tracer.instant(
                "router.probe_failure", cat="fleet", replica=r.idx,
                suspect=self._suspect[r.idx],
            )
            self._events.emit(
                "router.probe", replica=r.idx, ok=0,
                suspect=self._suspect[r.idx],
            )
            if self._suspect[r.idx] >= self.unhealthy_after or not r.alive:
                self._on_replica_death(r)

    def _probe(self, r) -> Optional[bool]:
        """True iff the replica answered its status probe in time; False
        on a failure/timeout; None when the probe WORKER never started
        inside the budget (a starved host scheduler — no evidence about
        the replica at all, so the caller must not move the suspect
        counter either way; counting it as a failure is how a loaded box
        false-positively killed perfectly healthy replicas). In
        background mode the probe runs under a deadline thread — a hung
        replica (injected `fleet.replica_hang` or a real wedge) shows up
        as a timeout, not a hung router."""
        if not self.background:
            try:
                r.probe()
                return True
            except Exception:  # noqa: BLE001 — any probe failure counts
                return False
        box: list = []
        started = threading.Event()

        def work():
            started.set()
            try:
                box.append(("ok", r.probe()))
            except BaseException as e:  # noqa: BLE001 — reported as unhealthy
                box.append(("err", e))

        t = threading.Thread(target=work, daemon=True)
        t.start()
        deadline = time.monotonic() + self.probe_timeout_s
        if not started.wait(self.probe_timeout_s):
            return None  # never scheduled: the box is starved, not the replica
        # The probe itself gets the remaining budget, floored at half —
        # a late-scheduled worker still deserves a real chance (the whole
        # point of the started gate), but total per-probe blocking stays
        # <= 1.5x the timeout so death detection doesn't crawl on a
        # loaded box.
        t.join(
            max(deadline - time.monotonic(), self.probe_timeout_s * 0.5)
        )
        if not box:
            # One short grace re-check: a long GIL hold (jit tracing on a
            # busy service) stalls THIS thread and the worker together,
            # so the deadline can expire with the trivial probe one
            # bytecode-quantum from finishing — when the holder releases,
            # the answer lands instantly. A real hang stays empty here
            # and costs only these extra milliseconds to declare.
            t.join(min(0.1, self.probe_timeout_s / 5))
        return bool(box) and box[0][0] == "ok"

    def _on_replica_death(self, r) -> None:
        """Remove the replica from the ring and requeue every unfinished
        job it held — resumed from its newest intact checkpoint generation
        when one exists, restarted fresh otherwise. Zero lost jobs either
        way.

        With the lease plane on, the member's lease is REVOKED (persisted)
        before anything is requeued, and each orphan's newest intact
        generation is re-sealed under the router's own lease — so if the
        "dead" replica is actually a zombie (hung, partitioned), every
        write it attempts from here on is provably stale: its fenced
        writes refuse themselves, and the one raced write that can slip
        through an already-open fd is rejected at load time by the stamp
        check. An injected `lease.revoke_race` fault aborts the whole
        death handling BEFORE any state changes; the next tick re-detects
        the death and re-runs it — revoke-then-requeue stays atomic."""
        with self._lock:
            if r.idx in self._dead:
                return
        member = lease_member(r.idx)
        if self.lease_store is not None:
            try:
                epoch = self.lease_store.revoke(member)
            except FaultError:
                # Injected lease.revoke_race: nothing was persisted and
                # nothing is requeued — the member stays (suspected)
                # alive until the next tick retries the revocation.
                self._tracer.instant(
                    "lease.revoke_race", cat="fleet", member=member
                )
                return
            except OSError:
                # The revocation did not durably land (store outage /
                # torn writes past the lease store's retries): abort the
                # WHOLE death handling — requeueing before a durable
                # revoke would hand the zombie a license to corrupt.
                # Next tick re-detects the death and retries.
                self._tracer.instant(
                    "lease.revoke_race", cat="fleet", member=member
                )
                return
            if epoch is not None:
                self.counters["lease_revokes"] += 1
                self._events.emit(
                    "lease.revoke", member=member, epoch=epoch
                )
        with self._lock:
            if r.idx in self._dead:
                return
            self._dead.add(r.idx)
            # A member dying DURING probation never made it back into the
            # ring; dropping the probation entry is the whole cleanup.
            self._probation.pop(r.idx, None)
            orphans = [
                fj for fj in self._jobs.values()
                if fj.replica == r.idx
                and fj.status not in FleetJobStatus.FINISHED
            ]
        self.counters["replica_crashes"] += 1
        self.ring.remove(r.idx)
        self._tracer.instant(
            "fleet.replica_dead", cat="fleet", replica=r.idx,
            orphans=len(orphans),
        )
        # The router is the single authority on fleet membership, so it
        # (not the replica driver) writes the one `replica.crash` event —
        # event counts stay consistent with the `replica_crashes` counter.
        self._events.emit(
            "replica.crash", replica=r.idx, error=r.error,
            orphans=len(orphans),
        )
        with self._tracer.span(
            "fleet.requeue", cat="fleet", replica=r.idx,
            orphans=len(orphans),
        ):
            for fj in orphans:
                with self._lock:
                    fj.requeues += 1
                    fj.replica = None
                    fj.handle = None
                    self.counters["requeued_jobs"] += 1
                resume = self._resume_token(fj, reseal=True)
                if resume is not None:
                    self.counters["restored_jobs"] += 1
                self._events.emit(
                    "job.requeued", job=fj.id, trace=fj.trace, src=r.idx,
                    restored=resume is not None,
                )
                self._place(fj, resume=resume)

    def _resume_token(self, fj: FleetJob, reseal: bool = False):
        """Probe the job's newest intact checkpoint generation; return a
        `ResumeToken` for the next replica to resolve, or None (restart
        fresh — still exact). With `reseal=True` (the death path — the
        writer's lease was JUST revoked) the generation is re-written
        under the router's own lease first: the revoked stamp it carries
        is legitimate (written before the revocation, which is exactly why
        this load accepts it with CRC-only validation), but every LATER
        read must be able to tell it from a zombie write — after the
        re-seal, anything still stamped with the revoked epoch is by
        definition post-revocation and gets rejected. The non-reseal paths
        (steal, lost-withdraw requeue — the checkpoint's writer is a LIVE
        member, its stamps valid by construction) use a cheap
        CRC-existence probe instead of parsing the whole npz on the
        supervisor tick thread; the receiving replica is the one that
        loads the bytes (through the fence) anyway."""
        if fj.ckpt_path is None:
            return None
        if reseal and self.router_lease is not None:
            try:
                # CRC-only load: the pre-revocation generation carries the
                # now-revoked stamp by construction.
                data, src = fenced_load_latest(fj.ckpt_path)
                arrays = {
                    k: data[k] for k in data.files
                    if k not in LEASE_STAMP_KEYS
                }
                fenced_savez(fj.ckpt_path, arrays, lease=self.router_lease)
                self.counters["lease_reseals"] += 1
            except (CheckpointCorrupt, FileNotFoundError, OSError):
                return None  # no intact generation: restart fresh
        else:
            src = latest_generation(fj.ckpt_path)
            if src is None:
                return None
        self._tracer.instant(
            "fleet.restore", cat="fleet", job=fj.id, src=src, trace=fj.trace
        )
        return ResumeToken(fj.ckpt_path)

    def _harvest(self) -> None:
        """Fold finished inner jobs into their fleet jobs. ERROR on a DEAD
        replica is left alone — the death handler requeues it; ERROR on a
        live replica (quarantine, bad model) is a real job failure."""
        with self._lock:
            open_jobs = [
                fj for fj in self._jobs.values()
                if fj.status not in FleetJobStatus.FINISHED
                and fj.handle is not None
            ]
        lost_steals: list = []
        for fj in open_jobs:
            inner = fj.handle._job
            if not inner.event.is_set():
                continue
            if fj.replica in self._dead:
                continue
            with self._lock:
                if fj.status in FleetJobStatus.FINISHED:
                    continue
                if inner.status == JobStatus.DONE:
                    fj.result = inner.result
                    self._finish(fj, FleetJobStatus.DONE)
                elif inner.status == JobStatus.ERROR:
                    r = self.replicas.get(fj.replica)
                    if r is not None and not r.alive:
                        continue  # death handler will requeue
                    fj.error = inner.error
                    self._finish(fj, FleetJobStatus.ERROR)
                elif inner.status == JobStatus.CANCELLED:
                    # A still-ROUTED fleet job whose inner copy is
                    # CANCELLED: a withdraw whose RESPONSE was lost (a
                    # remote steal hit its control deadline after the
                    # victim had already withdrawn — at-most-once RPC, the
                    # cross-process failure the in-proc fleet could never
                    # produce). The steal itself rebinds the handle in the
                    # same tick before harvest ever sees it, and the
                    # router's own cancel finishes the fleet job first, so
                    # what remains IS the lost-response case: recover like
                    # any orphan — requeue on the ring, zero lost jobs.
                    src = fj.replica
                    fj.requeues += 1
                    fj.replica = None
                    fj.handle = None
                    self.counters["requeued_jobs"] += 1
                    lost_steals.append((fj, src))
        for fj, src in lost_steals:
            resume = self._resume_token(fj)
            if resume is not None:
                with self._lock:
                    self.counters["restored_jobs"] += 1
            self._events.emit(
                "job.requeued", job=fj.id, trace=fj.trace, src=src,
                reason="withdraw response lost", restored=resume is not None,
            )
            self._place(fj, resume=resume)

    def _steal(self) -> None:
        """Idle replicas pull still-QUEUED jobs from the most-loaded
        replica (the `job_market.rs` split_and_push analogue at fleet
        scale). A queued job has no table state: the move is an atomic
        withdraw + fresh submit, and the `fleet.steal` fault point fires
        BEFORE the withdrawal so an injected fault leaves the job exactly
        where it was."""
        healthy = sorted(
            (
                r for r in self._healthy()
                # Probation members neither steal nor are stolen from:
                # keys (and work) move back only after promotion.
                if r.idx not in self._probation
            ),
            key=lambda r: r.idx,
        )
        if len(healthy) < 2:
            return
        # A draining member (scale-in in progress) must not PULL work —
        # it is leaving — but its queue stays stealable: the steals are
        # part of the drain.
        idle = [
            r for r in healthy
            if r.idle() and r.idx not in self._draining
        ]
        if not idle:
            return
        with self._lock:
            queued_by_replica: dict[int, list] = {}
            for fj in self._jobs.values():
                if (
                    fj.status in FleetJobStatus.FINISHED
                    or fj.handle is None
                    or fj.replica is None
                ):
                    continue
                if fj.handle._job.status == JobStatus.QUEUED:
                    queued_by_replica.setdefault(fj.replica, []).append(fj)
        for thief in idle:
            victims = [
                (len(v), idx) for idx, v in queued_by_replica.items()
                # Never steal from a SUSPECTED victim: its withdraw call
                # would stall the tick loop against a hung/partitioned
                # process, and if it is truly dead the death handler is
                # about to requeue its whole queue anyway.
                if v and idx != thief.idx and not self._suspect.get(idx)
            ]
            if not victims:
                return
            qlen, v_idx = max(victims)
            victim = self.replicas[v_idx]
            pool = queued_by_replica[v_idx]
            want = max(1, qlen // 2)
            moved = 0
            # Steal from the BACK of the queue (newest first) — the front
            # is about to be admitted where it already sits.
            while pool and moved < want:
                fj = pool.pop()
                try:
                    maybe_fault("fleet.steal", src=v_idx, dst=thief.idx)
                except FaultError:
                    return  # injected steal fault: job stays put
                with self._tracer.span(
                    "router.steal", cat="fleet", job=fj.id, src=v_idx,
                    dst=thief.idx, trace=fj.trace,
                ):
                    if not victim.withdraw(fj.handle.id):
                        continue  # admitted meanwhile: not stealable
                    # A stolen job may itself be a requeue carrying
                    # checkpointed progress (queued on the victim behind
                    # max_resident): the thief must resume from the newest
                    # intact generation, not restart the search (None when
                    # no generation exists yet). Count the restore so the
                    # journal's job.resumed events stay equal to the
                    # restored_jobs counter (the flight-recorder
                    # consistency pin).
                    resume = self._resume_token(fj)
                    if resume is not None:
                        with self._lock:
                            self.counters["restored_jobs"] += 1
                    try:
                        handle = thief.submit(
                            self._spec(fj, resume), fj.ckpt_path
                        )
                    except (FaultError, ReplicaDead):
                        # Thief died mid-steal: the job was already
                        # withdrawn, so place it like any orphan (never
                        # lost) — and account it like one too, so the
                        # journal's job.requeued events stay equal to the
                        # requeued_jobs counter.
                        with self._lock:
                            fj.replica = None
                            fj.handle = None
                            fj.requeues += 1
                            self.counters["requeued_jobs"] += 1
                        self._events.emit(
                            "job.requeued", job=fj.id, trace=fj.trace,
                            src=v_idx, reason="thief died mid-steal",
                        )
                        self._place(fj, resume=resume)
                        continue
                with self._lock:
                    if fj.status in FleetJobStatus.FINISHED:
                        # A fleet-level cancel raced the steal: don't leave
                        # the fresh inner copy running orphaned.
                        try:
                            handle.cancel()
                        except Exception:  # noqa: BLE001 — best-effort reap
                            pass
                        continue
                    fj.replica = thief.idx
                    fj.handle = handle
                    fj.steals += 1
                    self.counters["steals"] += 1
                self._tracer.instant(
                    "fleet.steal", cat="fleet", job=fj.id,
                    src=v_idx, dst=thief.idx, trace=fj.trace,
                )
                self._events.emit(
                    "fleet.steal", job=fj.id, trace=fj.trace,
                    src=v_idx, dst=thief.idx,
                )
                moved += 1

    # -- reporting -------------------------------------------------------------

    def all_done(self) -> bool:
        with self._lock:
            return all(
                fj.status in FleetJobStatus.FINISHED
                for fj in self._jobs.values()
            )

    def stats(self) -> dict:
        """Fleet-level counters (obs/schema.py FLEET_COUNTER_KEYS) — the
        router's `/.status` body and `/metrics` source."""
        with self._lock:
            by_status: dict[str, int] = {}
            for fj in self._jobs.values():
                by_status[fj.status] = by_status.get(fj.status, 0) + 1
            per_replica = {
                str(r.idx): r.snapshot_row()
                for r in self.replicas.values()
            }
            return {
                "replicas": len(self.replicas),
                "healthy": len(self._healthy()),
                "jobs": by_status,
                "queued": sum(
                    row.get("queued", 0) for row in per_replica.values()
                ),
                **self.counters,
                # Router-process fencing refusals/rejections (each REMOTE
                # replica's own counts live in its process's "lease"
                # registry source, scraped from its /metrics).
                "lease_rejected": (
                    self.lease_store.rejected_total()
                    if self.lease_store is not None else 0
                ),
                "per_replica": per_replica,
                # Last-N flight-recorder events — the `/.status` at-a-
                # glance ring ([] when the fleet journals nothing; the
                # registry's flatten drops it from /metrics, where
                # unbounded label text does not belong).
                "events_recent": self._events.recent(16),
            }

    def metrics(self) -> dict:
        return self.stats()

    def events_tail(
        self, job_id: Optional[int] = None, since: int = 0,
        wait_s: float = 0.0,
    ) -> tuple:
        """Flight-recorder tail over the ROUTER journal (fleet-level job
        ids) — the `GET /jobs/<id>/events` long-poll primitive; replica
        journals are merged offline by obs/timeline.py."""
        return self._events.tail(since=since, job=job_id, wait_s=wait_s)

    def close(self) -> None:
        REGISTRY.unregister(self._metrics_name)
        # Release a chaos plan that adopted this router's recorder (the
        # plan may outlive the fleet; see CheckService.close).
        plan = active_plan()
        if plan is not None and plan.events is self._events:
            plan.events = None


# -- HTTP front door -----------------------------------------------------------


def fleet_status_view(router: FleetRouter) -> dict:
    return {
        **router.stats(),
        "job_rows": [router.poll(jid) for jid in router.job_ids()],
    }


def serve_fleet(
    fleet,
    address: str = "localhost:3500",
    registry=None,
    block: bool = False,
):
    """Start the fleet HTTP front door; same handle shape as
    `serve_service`. `fleet` is a ServiceFleet (or anything exposing
    `.router`); models are named through the same ModelRegistry."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ..explorer.server import ExplorerServer
    from ..obs import render_prometheus
    from .server import RETRY_AFTER_S, ModelRegistry, events_view

    router = fleet.router
    reg = registry if registry is not None else ModelRegistry()
    host, _, port = address.partition(":")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _json(self, obj, code=200, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _text(self, body: str, code=200):
            data = body.encode()
            self.send_response(code)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _503(self, msg: str) -> None:
            self._json(
                {"error": msg}, 503, headers={"Retry-After": RETRY_AFTER_S}
            )

        def _429(self, e: QuotaExceeded) -> None:
            # Quota rejections are retryable by contract: the Retry-After
            # is computed from the tenant's actual refill rate, so a
            # well-behaved client that honors it succeeds on the retry.
            self._json(
                {"error": str(e), "tenant": e.tenant, "reason": e.reason},
                429,
                headers={"Retry-After": str(e.retry_after_s)},
            )

        def _injected_503(self, method: str) -> bool:
            try:
                maybe_fault("service.http", method=method, path=self.path)
            except FaultError as e:
                # The 503 surface is part of the flight recording: the
                # forensic pass can see the front door bouncing clients.
                router._events.emit(
                    "router.unavailable",
                    reason=f"injected http fault ({method})",
                )
                router._tracer.instant(
                    "router.unavailable", cat="fleet", method=method
                )
                self._503(f"injected fault: {e}")
                return True
            return False

        def _job_id(self, suffix: str = "") -> Optional[int]:
            raw = self.path.partition("?")[0][len("/jobs/"):]
            if suffix:
                if not raw.endswith(suffix):
                    return None
                raw = raw[: -len(suffix)]
            try:
                return int(raw.strip("/"))
            except ValueError:
                return None

        def do_GET(self):
            if self._injected_503("GET"):
                return
            path, _, query = self.path.partition("?")
            try:
                if path == "/.status":
                    self._json(fleet_status_view(router))
                    return
                if path == "/metrics":
                    self._text(render_prometheus(REGISTRY.collect()))
                    return
                if path.startswith("/jobs/"):
                    if path.endswith("/events"):
                        jid = self._job_id("/events")
                        if jid is not None:
                            router._get(jid)  # 404 on unknown jobs
                            self._json(events_view(router, jid, query))
                            return
                    jid = self._job_id()
                    if jid is not None:
                        self._json(router.poll(jid))
                        return
                self._json({"error": "not found"}, 404)
            except KeyError as e:
                self._json({"error": str(e)}, 404)

        def do_POST(self):
            if self._injected_503("POST"):
                return
            try:
                if self.path == "/jobs":
                    n = int(self.headers.get("Content-Length") or 0)
                    try:
                        payload = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError:
                        self._json({"error": "bad JSON body"}, 400)
                        return
                    if "model" not in payload:
                        self._json({"error": "missing 'model'"}, 400)
                        return
                    name = payload["model"]
                    args = dict(payload.get("args") or {})
                    opts = dict(payload.get("opts") or {})
                    tenant = payload.get("tenant") or DEFAULT_TENANT
                    fw = opts.pop("finish_when", None)
                    if fw is not None:
                        opts["finish_when"] = {
                            "all": HasDiscoveries.ALL,
                            "any": HasDiscoveries.ANY,
                            "all_failures": HasDiscoveries.ALL_FAILURES,
                            "any_failures": HasDiscoveries.ANY_FAILURES,
                        }[fw]
                    model = reg.get(name, args)
                    # Stable HTTP route key: registry name + args, so
                    # same-config submissions share a replica's compiled
                    # step across unrelated clients.
                    key = name + "".join(
                        f":{k}={v}" for k, v in sorted(args.items())
                    )
                    try:
                        # model_ref rides along so REMOTE replicas can
                        # resolve the same (name, args) through their own
                        # registry — in-proc replicas just ignore it.
                        h = router.submit(
                            model, route_key=key,
                            model_ref=(name, args), tenant=tenant, **opts,
                        )
                    except NoHealthyReplica as e:
                        self._503(str(e))
                        return
                    except QuotaExceeded as e:
                        self._429(e)
                        return
                    self._json({"job": h.id})
                    return
                if self.path.startswith("/jobs/") and self.path.endswith(
                    "/cancel"
                ):
                    jid = self._job_id("/cancel")
                    if jid is not None:
                        self._json({"cancelled": router.cancel(jid)})
                        return
                self._json({"error": "not found"}, 404)
            except KeyError as e:
                self._json({"error": str(e)}, 404)
            except Exception as e:  # noqa: BLE001 — bad submits must not kill
                self._json({"error": f"{type(e).__name__}: {e}"}, 400)

    httpd = ThreadingHTTPServer(
        (host or "localhost", int(port or 3500)), Handler
    )
    if block:
        server = ExplorerServer(httpd, fleet, None)
        try:
            httpd.serve_forever()
        finally:
            httpd.server_close()
        return server
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return ExplorerServer(httpd, fleet, thread)
