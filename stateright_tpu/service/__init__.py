"""Check service: a continuous-batching multi-job scheduler over shared
device state tables.

The standalone engines (`spawn_tpu`, FrontierSearch/ResidentSearch) own the
whole device for one check. This package is the serving layer above them —
the model-checking twin of continuous-batching inference servers (Orca) and
of swarm verification: one persistent `CheckService` multiplexes many
concurrent check jobs onto one device, packing their frontier lanes into
shared fused steps and admitting/retiring/preempting jobs mid-flight.

Why it is sound to share ONE device hash table (and one tiered spill
store) across jobs: every key is job-salted (tensor/fingerprint.salt_fp),
a per-job bijection of the fingerprint space — within-job dedup is
bit-identical to a standalone run, cross-job collisions are as improbable
as any two unrelated 64-bit fingerprints, and unsalting (the same
involution) hands back discovery fingerprints bit-identical to a
single-job run.

Pieces:

- `queue`     — admission queue + per-job frontier/counters/salt.
- `scheduler` — the continuous-batching engine: shared table, one fused
                step per model group, waterfilled round-robin lane grants,
                FrontierSearch-parity bookkeeping.
- `api`       — `CheckService.submit(model, ...) -> JobHandle`
                (poll/result/cancel), preemption + timeouts, and the
                `Checker`-shaped adapter behind
                `model.checker().spawn_service(service)`.
- `server`    — HTTP front end (`serve_service`): POST /jobs, GET
                /jobs/<id>, cancel, `/.status` with per-job metrics.
- `metrics`   — per-job queue wait / device steps / lanes held /
                preemptions / spill share.
- `router`    — the fleet front door: consistent-hash routing across N
                replicas, health probes (jittered exponential backoff for
                failing members), bounded retry, replica failure →
                lease revocation + checkpoint requeue-resume,
                cross-replica work stealing, and the fleet HTTP server
                (`serve_fleet`).
- `fleet`     — `Replica` crash-only drivers + the `ServiceFleet`
                assembly (one router + N CheckService replicas, in-proc
                or — `remote=True` — one subprocess per replica over a
                shared store root).
- `lease`     — epoch-fenced checkpoint leases: the router revokes a dead
                member's lease before requeueing, every replica write
                path stamps + re-validates its epoch, and a zombie's
                stale writes are refused or rejected, never read back.
- `remote`    — the HTTP replica stub (`RemoteReplica`), the per-host
                server (`serve_replica`), and the subprocess spawner
                behind `ServiceFleet(remote=True)`.
- `tenancy`   — per-tenant identity, quotas (in-flight cap +
                windowed lane-seconds budget → `QuotaExceeded`/HTTP 429
                with Retry-After), and the corpus namespace salt.
- `autoscale` — the reconciliation loop (`Autoscaler`) that grows and
                shrinks a ServiceFleet from queue depth, lane
                utilization, and p99 admission latency, with hysteresis
                bands and cooldowns.
"""

from .api import CheckService, JobHandle, ServiceChecker
from .autoscale import AutoscaleConfig, Autoscaler
from .fleet import Replica, ServiceFleet
from .lease import FencedEvents, Lease, LeaseRevoked, LeaseStore
from .metrics import JobMetrics
from .queue import Job, JobResume, JobStatus
from .router import (
    FleetJobHandle,
    FleetJobStatus,
    FleetRouter,
    HashRing,
    NoHealthyReplica,
    ReplicaDead,
    ResumeToken,
    lease_member,
    serve_fleet,
)
from .scheduler import ServiceEngine, ServiceError
from .server import ModelRegistry, default_registry, serve_service, status_view
from .tenancy import (
    DEFAULT_TENANT,
    QuotaExceeded,
    TenantQuota,
    TenantQuotas,
)

__all__ = [
    "CheckService",
    "AutoscaleConfig",
    "Autoscaler",
    "DEFAULT_TENANT",
    "QuotaExceeded",
    "TenantQuota",
    "TenantQuotas",
    "JobHandle",
    "ServiceChecker",
    "JobMetrics",
    "Job",
    "JobResume",
    "JobStatus",
    "ServiceEngine",
    "ServiceError",
    "ModelRegistry",
    "default_registry",
    "serve_service",
    "status_view",
    "Replica",
    "ServiceFleet",
    "FleetRouter",
    "FleetJobHandle",
    "FleetJobStatus",
    "HashRing",
    "NoHealthyReplica",
    "ReplicaDead",
    "ResumeToken",
    "lease_member",
    "serve_fleet",
    "Lease",
    "LeaseRevoked",
    "LeaseStore",
    "FencedEvents",
]
