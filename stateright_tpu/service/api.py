"""Async client API of the check service.

`CheckService` is the persistent front door: `submit(model, ...)` returns a
`JobHandle` immediately; a scheduler thread packs every admitted job's
frontier lanes into shared fused device steps (continuous batching — see
scheduler.py) until each job finishes, is cancelled, or times out. All jobs
share ONE device hash table via job-salted fingerprints, so a service
outlives any single check the way an inference server outlives any single
request.

Scheduling policy:

- admission: jobs wait in a priority queue; at most `max_resident` jobs
  hold lanes at once (None = unlimited — continuous batching itself is the
  fairness mechanism then).
- fairness: per-step lane grants are waterfilled round-robin across a
  group's runnable jobs, and the grant rotation advances every step.
- preemption: with `preempt_steps=N`, a job that has consumed N device
  steps since admission while others wait is parked (its frontier spilled
  through the checkpoint machinery when `spill_dir` is set) and re-queued
  behind its priority class; its visited set stays in the shared table, so
  resumption is exact.
- cancellation (`JobHandle.cancel()`) drops the job's frontier on the spot;
  its lanes are free for other jobs at the very next step — no batch drain.

Synchronous use: `CheckService(background=False)` runs no thread; tests and
scripts drive it deterministically with `pump()` / `drain()`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

from ..core.discovery import HasDiscoveries
from ..checker.base import Checker
from ..faults.ckptio import CheckpointCorrupt
from ..faults.plan import active_plan
from ..obs import (
    REGISTRY,
    TERMINAL_EVENT_BY_STATUS,
    EventJournal,
    LogHistogram,
    Tracer,
    as_events,
    as_tracer,
    mint_trace_id,
)
from .queue import AdmissionQueue, Job, JobStatus
from .scheduler import ServiceEngine, ServiceError, StepFault
from .tenancy import DEFAULT_TENANT, QuotaExceeded, TenantQuotas


class JobHandle:
    """Client-side handle to a submitted job (the service analogue of the
    `Checker` handle a spawn returns)."""

    def __init__(self, service: "CheckService", job: Job):
        self._service = service
        self._job = job

    @property
    def id(self) -> int:
        return self._job.id

    def status(self) -> str:
        return self._job.status

    def poll(self) -> dict:
        return self._service.poll(self._job.id)

    def result(self, wait: bool = True, timeout: Optional[float] = None):
        """The job's SearchResult. Raises on cancelled/errored jobs; with
        wait=False returns None while the job is still in flight."""
        return self._service.result(self._job.id, wait=wait, timeout=timeout)

    def cancel(self) -> bool:
        return self._service.cancel(self._job.id)

    def discoveries(self) -> dict:
        """{property name: Path} — reconstructed through the shared table's
        salted parent chain (scheduler.reconstruct_path)."""
        return self._service.discovery_paths(self._job.id)

    def metrics(self) -> dict:
        return self._job.metrics.to_dict(self._job.unique_count)

    def as_checker(self) -> "ServiceChecker":
        return ServiceChecker(self)


class CheckService:
    def __init__(
        self,
        batch_size: int = 1024,
        table_log2: int = 20,
        insert_variant: str = "sort",
        store: str = "device",
        high_water: float = 0.85,
        low_water: Optional[float] = None,
        summary_log2: int = 20,
        max_resident: Optional[int] = None,
        preempt_steps: Optional[int] = None,
        spill_dir: Optional[str] = None,
        background: bool = True,
        telemetry: bool = True,
        telemetry_log2: int = 12,
        trace_out: Optional[str] = None,
        retry_limit: int = 2,
        events=None,
        events_out: Optional[str] = None,
        corpus_dir: Optional[str] = None,
        quotas: Optional[TenantQuotas] = None,
        quota_gate: bool = True,
    ):
        """`telemetry=True` records one step-metrics row per fused device
        step (obs/ring.py; digest in `stats()["telemetry"]`, `/.status`,
        and `/metrics`). `trace_out=<path>` records the service lifecycle
        (admission, fused steps, eviction, preemption, finalize) as Chrome
        trace-event JSON — flushed periodically (obs/trace.py cadence) so
        a crash leaves a loadable partial trace, and saved on `close()` —
        load it in Perfetto.

        `events` / `events_out=<path>` attach the flight recorder
        (obs/events.py): every job lifecycle transition (submit, admit,
        preempt, resume, quarantine, done/cancelled/error) and every fused
        step lands in the append-only JSONL journal, keyed by the job's
        `trace` id; `GET /jobs/<id>/events` on the HTTP front end tails
        it live. Pass an `EventJournal` to share one (the fleet's
        per-replica wiring) or a path to own one.

        `corpus_dir=<path>` turns on the cross-job warm-start corpus
        (store/corpus.py; requires `store="tiered"`): completed exhaustive
        jobs publish their visited set as a content-addressed, CRC-checked
        generation there, and a later submission whose content key (model
        definition x lowering config x finish policy) matches preloads it
        into the spill tier + Bloom summary — the repeat check completes
        ≥5x faster with bit-identical results. Fleet replicas pointed at
        ONE directory share generations (ServiceFleet(corpus_dir=...)).
        Corrupt entries are detected by the ckptio CRC footer and ignored
        (cold run, never wrong results). The corpus also powers Spec-CI
        (store/specdelta.py, `python -m stateright_tpu.ci`): an EDITED
        model definition of the same spec geometry is diffed against the
        family's per-component digests, and a properties-only or
        boundary-only edit still warm-starts on the "delta" rung.

        `quotas` (a service/tenancy.py TenantQuotas) arms per-tenant
        admission control: submissions carrying a non-default `tenant=`
        are gated on the tenant's in-flight cap and lane-seconds budget
        (over-quota raises tenancy.QuotaExceeded → HTTP 429 with
        Retry-After on the front ends), and each successful fused step
        charges its lane-seconds against the submitting tenant. The
        default tenant is never gated, so tenant-less deployments are
        unchanged. `quota_gate=False` keeps the CHARGING but disables
        the admission gate — how fleet replicas run: the FleetRouter is
        the single admission authority, and a requeued/stolen job
        re-submitted here must never bounce off a budget its first
        admission already passed (that would turn a replica death into
        a quota-shaped job loss).

        `retry_limit` is the per-group step-fault budget: a group whose
        fused step keeps failing is retried that many times (the faulted
        lanes were pushed back, so retries are exact), then each job is
        probed SOLO and only the job(s) whose step fails in isolation are
        quarantined — one poison job cannot kill its group, let alone the
        service (see scheduler.StepFault)."""
        self._trace_out = trace_out
        self._tracer = as_tracer(
            Tracer(annotate=True, out=trace_out) if trace_out else None
        )
        self._events_owned = None
        if events is None and events_out:
            events = self._events_owned = EventJournal(
                events_out, writer="service"
            )
        self._events = as_events(events)
        self._engine = ServiceEngine(
            batch_size=batch_size,
            table_log2=table_log2,
            insert_variant=insert_variant,
            store=store,
            high_water=high_water,
            low_water=low_water,
            summary_log2=summary_log2,
            telemetry=telemetry,
            telemetry_log2=telemetry_log2,
            tracer=self._tracer if trace_out else None,
            events=events,
            corpus_dir=corpus_dir,
            quotas=quotas,
        )
        self.quotas = quotas
        self._quota_gate = bool(quota_gate)
        self._quota_rejected = 0
        # Bounded recent queue-wait samples (seconds) — the autoscaler's
        # p99 admission-latency signal, appended at each first admission.
        self._queue_waits: deque = deque(maxlen=256)
        # Prometheus-shaped distributions behind the two autoscaler
        # signals: queue waits in ms, lane occupancy in 0..1. The
        # `/.status` scalars above stay; these add `*_bucket`/`_sum`/
        # `_count` text on both `/metrics` front doors.
        self._adm_hist = LogHistogram()
        self._lane_hist = LogHistogram(lo=1.0 / 128, hi=1.0)
        # Central counter registry (obs/registry.py): both HTTP front ends'
        # `/metrics` render every registered source; weakly held, so a
        # dropped service unregisters itself.
        self._metrics_name = REGISTRY.register("service", self.metrics)
        self.max_resident = max_resident
        self.preempt_steps = preempt_steps
        self.spill_dir = spill_dir
        self.retry_limit = retry_limit
        self._adm = AdmissionQueue()
        self._jobs: dict[int, Job] = {}
        # Jobs finished but not yet completed: (job, status, publish
        # payload) triples whose off-lock half (_drain_finalizers) still
        # has to run — corpus npz write, result build, event, wakeup.
        self._finalizing: list = []
        # Fire-and-forget corpus publish payloads from the NON-finalize
        # terminal/park paths (cancel, preemption, shutdown): partial
        # entries whose npz write must still happen off-lock, but whose
        # job needs no result/event completion here.
        self._publishing: list = []
        self._next_id = 1
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._closed = False
        self._failed: Optional[str] = None
        self._thread = None
        if background:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    # -- client surface --------------------------------------------------------

    def submit(
        self,
        model,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        target_state_count: Optional[int] = None,
        target_max_depth: Optional[int] = None,
        timeout: Optional[float] = None,
        priority: int = 0,
        journal: bool = False,
        resume=None,
        trace: Optional[str] = None,
        tenant: str = DEFAULT_TENANT,
    ) -> JobHandle:
        """Enqueue a check job; returns immediately. The model must be a
        TensorModel; submit the SAME model instance for jobs that should
        share a compiled step (and batch lanes) with each other.

        `journal=True` records the job's (fp, parent fp) claims host-side
        so a fleet replica can checkpoint it for requeue-resume; `resume`
        (a queue.JobResume) admits the job mid-search from such a
        checkpoint — both are the service fleet's plumbing (service/
        fleet.py), not a client-facing knob. `trace` is the flight-recorder
        correlation id: the fleet router mints one at ITS front door and
        passes it through here, so the job's events on every replica key
        to one timeline; a direct submission mints its own.

        `tenant` is the tenancy-plane identity (service/tenancy.py): it
        scopes quota enforcement, the two-level fair-share waterfill, and
        the corpus namespace. Over-quota submissions raise
        `tenancy.QuotaExceeded` (→ 429 + Retry-After on the HTTP front
        ends). The default tenant is gate-free and byte-identical to the
        pre-tenancy behavior."""
        from ..tensor.model import TensorModel

        if not isinstance(model, TensorModel):
            raise TypeError(
                "CheckService.submit requires a stateright_tpu.tensor."
                f"TensorModel; got {type(model).__name__}"
            )
        # Corpus prefetch OFF the service lock (ROADMAP item 4 leftover):
        # the content-key jaxpr trace and the entry npz read+decode happen
        # on the CLIENT thread before the lock is ever taken — a slow
        # corpus read can no longer stall an unrelated job's poll. The
        # probe Job is thrown away if admission control rejects below.
        prefetch: Optional[Job] = None
        if self._engine.has_corpus:
            prefetch = Job(
                0, model,
                finish_when=finish_when,
                target_state_count=target_state_count,
                target_max_depth=target_max_depth,
                tenant=tenant,
            )
            try:
                self._engine.prefetch_warm(prefetch)
            except Exception:  # noqa: BLE001 — warm-start is an optimization
                prefetch = None
        with self._work:
            if self._closed:
                # srlint: fault-ok caller-contract guard, not an I/O/device surface
                raise RuntimeError("service is closed")
            if self._failed:
                raise ServiceError(self._failed)
            if (
                self.quotas is not None and self._quota_gate
                and tenant != DEFAULT_TENANT
            ):
                # Live in-flight scan (no release bookkeeping to leak):
                # finished jobs simply stop counting.
                in_flight = sum(
                    1 for j in self._jobs.values()
                    if j.tenant == tenant
                    and j.status not in JobStatus.FINISHED
                )
                try:
                    self.quotas.admit(tenant, in_flight)
                except QuotaExceeded:
                    self._quota_rejected += 1
                    self._events.emit("job.quota_rejected", tenant=tenant)
                    raise
            job = Job(
                self._next_id,
                model,
                finish_when=finish_when,
                target_state_count=target_state_count,
                target_max_depth=target_max_depth,
                timeout=timeout,
                priority=priority,
                # The warm-start corpus publishes from the journal (the
                # job's full unsalted visited set), so a corpus-enabled
                # service journals every job.
                journal=journal or self._engine.has_corpus,
                resume=resume,
                trace=trace or mint_trace_id(),
                tenant=tenant,
            )
            if prefetch is not None:
                job.content_key = prefetch.content_key
                job.warm_entry = prefetch.warm_entry
                job.warm_entry_kind = prefetch.warm_entry_kind
                job.warm_checked = prefetch.warm_checked
                # The off-lock prefetch already seeded the canonical verdict
                # cache (scheduler.prefetch_warm); carry the count so the
                # real job's detail["corpus"] reports it.
                job.verdict_preloads = prefetch.verdict_preloads
                # Spec-CI rung state (scheduler._delta_lookup runs inside
                # the prefetch): the named edit class, the "delta" partial
                # kind, and the no-publish mark on widened continuations.
                job.delta_class = prefetch.delta_class
                job.partial_kind = prefetch.partial_kind
                job.no_publish = prefetch.no_publish
            self._next_id += 1
            self._jobs[job.id] = job
            self._adm.push(job)
            self._events.emit(
                "job.submitted", job=job.id, trace=job.trace,
                resumed=bool(resume) or None,
            )
            self._work.notify_all()
            return JobHandle(self, job)

    def withdraw(self, job_id: int) -> bool:
        """Atomically remove a still-QUEUED job (the fleet work-stealing
        primitive: a queued job has no table state, so moving it to another
        replica is a clean cancel-here/submit-there). Returns False once
        the job was admitted (or finished) — stealing running jobs is the
        checkpoint plane's business, not the queue's. Deliberately emits
        no journal event: the router's `fleet.steal` records the move, and
        the job's trace continues on the thief replica."""
        job = self._get(job_id)
        with self._work:
            if job.status != JobStatus.QUEUED:
                return False
            self._adm.remove(job)
            job.status = JobStatus.CANCELLED
            job.metrics.finished_at = time.monotonic()
            job.event.set()
            self._idle.notify_all()
            return True

    def poll(self, job_id: int) -> dict:
        job = self._get(job_id)
        with self._lock:
            return {
                "id": job.id,
                "status": job.status,
                "trace": job.trace,
                "state_count": job.state_count,
                "unique_state_count": job.unique_count,
                "max_depth": job.max_depth,
                "steps": job.metrics.device_steps,
                "pending_lanes": job.pending_lanes,
                "discoveries": sorted(job.discoveries),
                "error": job.error,
                "quarantined": job.quarantined,
                "metrics": job.metrics.to_dict(job.unique_count),
            }

    def result(
        self, job_id: int, wait: bool = True, timeout: Optional[float] = None
    ):
        job = self._get(job_id)
        if wait:
            if not job.event.wait(timeout):
                raise TimeoutError(f"job {job_id} still running")
        elif not job.event.is_set():
            return None
        if job.status == JobStatus.CANCELLED:
            # srlint: fault-ok caller-contract guard (cancellation is the caller's own act)
            raise RuntimeError(f"job {job_id} was cancelled")
        if job.status == JobStatus.ERROR:
            raise ServiceError(job.error or f"job {job_id} failed")
        return job.result

    def cancel(self, job_id: int) -> bool:
        """Cancel a job mid-flight. Its frontier lanes are reclaimed at the
        next scheduling round; already-inserted table entries stay (salted,
        so they shadow nothing). Returns False once the job had finished."""
        job = self._get(job_id)
        with self._work:
            if job.status in JobStatus.FINISHED:
                return False
            self._adm.remove(job)
            job.status = JobStatus.CANCELLED
            # Partial-publish what the job visited (corpus v2) BEFORE
            # retire drops the frontier and the journal is released — a
            # cancelled check's successor warm-starts from the cut.
            payload = self._engine.prepare_publish(job)
            if payload is not None:
                self._publishing.append(payload)
            self._engine.retire(job)
            job.metrics.finished_at = time.monotonic()
            job.journal = None  # finished: no checkpoint consumer
            self._events.emit(
                "job.cancelled", job=job.id, trace=job.trace
            )
            job.event.set()
            self._work.notify_all()
            self._idle.notify_all()
        self._drain_publishes()  # npz write off-lock, on the caller
        return True

    def discovery_paths(self, job_id: int) -> dict:
        job = self._get(job_id)
        with self._lock:
            return {
                name: self._engine.reconstruct_path(job, fp)
                for name, fp in job.discoveries.items()
            }

    def job_ids(self) -> list:
        with self._lock:
            return sorted(self._jobs)

    def stats(self) -> dict:
        """Service-level counters for dashboards and the HTTP `/.status`."""
        with self._lock:
            by_status: dict[str, int] = {}
            for j in self._jobs.values():
                by_status[j.status] = by_status.get(j.status, 0) + 1
            out = {
                "jobs": by_status,
                "queued": len(self._adm),
                # The autoscaler-signal pair, in the same vocabulary as
                # the fleet's per-replica rows (fleet.Replica._signal_row)
                # so one dashboard reads both deployments.
                "lane_util": round(self._engine.lane_util(), 4),
                "adm_p99_ms": self.admission_p99_ms(),
                "device_steps": self._engine.total_steps,
                "groups": len(self._engine.groups),
                "table_fill": round(
                    self._engine.hot_claims / self._engine.table.size, 4
                ),
                "store": self._engine.store_stats(),
                # Step-telemetry digest (obs/ring.py) — merged into the
                # HTTP `/.status` through this dict.
                "telemetry": self._engine.telemetry_summary(),
                # Robustness counters (step faults absorbed, exact
                # retries, quarantined poison jobs) — the service half of
                # the chaos plane's accounting.
                "faults": dict(self._engine.fault_counters),
            }
            # Warm-start corpus counters (store/corpus.py) — present only
            # on corpus-enabled services so plain deployments' `/.status`
            # stays byte-identical to before.
            corpus = self._engine.corpus_stats()
            if corpus is not None:
                out["corpus"] = corpus
            # Tenancy accounting — present only on quota-armed services,
            # so plain deployments' `/.status` stays byte-identical.
            if self.quotas is not None:
                out["tenants"] = self.quotas.snapshot()
                out["quota_rejected"] = self._quota_rejected
            # Measured-vs-predicted calibration join (obs/calib.py) —
            # present only once the comparator has closed a chunk, so
            # calibration-less deployments' `/.status` stays byte-identical.
            calib = self._engine.calib_detail()
            if calib is not None:
                out["calib"] = calib
            return out

    def lane_util(self) -> float:
        """Last fused step's batch occupancy (0..1) — the autoscaler's
        per-replica lane-utilization signal (also in snapshot_row)."""
        with self._lock:
            return self._engine.lane_util()

    def admission_p99_ms(self) -> float:
        """p99 of recent queue waits, milliseconds (0.0 before any
        admission) — the autoscaler's latency signal."""
        with self._lock:
            waits = sorted(self._queue_waits)
        if not waits:
            return 0.0
        idx = min(len(waits) - 1, int(0.99 * len(waits)))
        return round(waits[idx] * 1e3, 3)

    def store_stats(self) -> Optional[dict]:
        with self._lock:
            return self._engine.store_stats()

    def telemetry_summary(self) -> Optional[dict]:
        with self._lock:  # a scrape must not race the scheduler's appends
            return self._engine.telemetry_summary()

    def table_fill(self) -> float:
        """The shared table's fill fraction alone — the reporter's per-tick
        read, without rebuilding the whole stats()/telemetry digest."""
        with self._lock:
            return round(
                self._engine.hot_claims / self._engine.table.size, 4
            )

    def drift_ratio(self) -> Optional[float]:
        """Last closed calibration chunk's measured/predicted ratio
        (obs/calib.py) — the reporter's `drift=` read; lock-free plain
        attribute access like the fleet's signal row."""
        calib = self._engine._calib
        return calib.drift_ratio() if calib is not None else None

    def metrics(self) -> dict:
        """Flat counters for the obs registry / `GET /metrics` (service
        stats plus the engine's step digest; per-job rows stay in
        `/.status` — unbounded label cardinality does not belong in
        Prometheus gauges)."""
        out = self.stats()
        # Real histograms (registry.LogHistogram) for the two autoscaler
        # signals — render_prometheus turns each into a `*_bucket`/`_sum`/
        # `_count` triplet on both `/metrics` doors.
        out["admission_wait_ms"] = self._adm_hist
        out["lane_util_window"] = self._lane_hist
        return out

    def events_tail(
        self, job_id: Optional[int] = None, since: int = 0,
        wait_s: float = 0.0,
    ) -> tuple:
        """Flight-recorder tail (the `GET /jobs/<id>/events` long-poll
        primitive): `(events, next_cursor)` with cursor >= `since`,
        filtered to `job_id` when given. ([], since) with no recorder."""
        return self._events.tail(since=since, job=job_id, wait_s=wait_s)

    # -- scheduling ------------------------------------------------------------

    def _get(self, job_id: int) -> Job:
        with self._lock:
            try:
                return self._jobs[job_id]
            except KeyError:
                raise KeyError(f"no such job {job_id}") from None

    def _resident(self) -> list:
        return [
            j for j in self._jobs.values() if j.status == JobStatus.RUNNING
        ]

    def _has_work(self) -> bool:
        return bool(
            (len(self._adm) and self._admittable())
            or any(g.runnable() for g in self._engine.groups.values())
        )

    def _admittable(self) -> bool:
        return (
            self.max_resident is None
            or len(self._resident()) < self.max_resident
        )

    def _finalize(self, job: Job, status: str = JobStatus.DONE) -> None:
        """Mark a job finished (under the lock) and queue its completion
        work. The EXPENSIVE half of finishing — the corpus publish's npz
        write + Bloom rehash — runs OFF the service lock in
        `_drain_finalizers` (the caller's loop drains right after the lock
        is released), so a slow publish can no longer stall an unrelated
        job's poll. The job's result/event land there too, AFTER the
        publish, so `detail["corpus"]["published"]` stays truthful."""
        self._tracer.instant(
            "service.finalize", cat="service", job=job.id, status=status,
            trace=job.trace,
        )
        job.status = status
        job.metrics.finished_at = time.monotonic()
        # Under-lock half of the publish: gate + journal/frontier snapshot
        # (memory concatenation only). MUST run before retire — retire
        # drops the frontier a partial publish snapshots.
        payload = self._engine.prepare_publish(job)
        self._engine.retire(job)
        self._finalizing.append((job, status, payload))

    def _drain_publishes(self) -> None:
        """Write out fire-and-forget partial-publish payloads (cancel /
        preemption / shutdown cuts) off-lock. Chaos-covered like every
        publish: an aborted write degrades to an unpublished entry, never
        a wrong one."""
        while True:
            with self._lock:
                if not self._publishing:
                    return
                payload = self._publishing.pop(0)
            self._engine.publish_payload(payload)  # never raises

    def _drain_finalizers(self) -> None:
        """Complete every deferred finalize: publish off-lock, then (back
        under the lock) build the result, release the journal, emit the
        terminal event, and wake waiters. Called with the service lock
        NOT held (pump()/_loop() drain after releasing it; close() after
        joining the scheduler thread)."""
        self._drain_publishes()
        while True:
            with self._lock:
                if not self._finalizing:
                    return
                job, status, payload = self._finalizing.pop(0)
            published = False
            if payload is not None:
                # The slow half (Bloom rehash + crash-atomic npz write) —
                # no lock held; never raises.
                published = self._engine.publish_payload(payload)
            with self._lock:
                if payload is not None:
                    job.published = published
                job.result = self._engine.build_result(job)
                # The journal (the job's full visited set, ~16 B/state)
                # has no consumer past this point — finished jobs are
                # never checkpointed or resumed — and finished Job objects
                # stay in self._jobs for the service lifetime, so release
                # it or a long-lived corpus-enabled service (journal
                # forced on) grows with every job ever served.
                job.journal = None
                self._events.emit(
                    TERMINAL_EVENT_BY_STATUS[status],
                    job=job.id, trace=job.trace,
                    states=job.state_count, unique=job.unique_count,
                    timed_out=job.timed_out or None,
                )
                job.event.set()
                self._idle.notify_all()

    def _expire_timeouts(self) -> None:
        now = time.monotonic()
        for job in list(self._jobs.values()):
            if job.status in JobStatus.FINISHED or job.deadline is None:
                continue
            if now > job.deadline:
                self._adm.remove(job)
                job.timed_out = True
                self._finalize(job)

    def _admit_waiting(self) -> None:
        while len(self._adm) and self._admittable():
            job = self._adm.pop_next()
            if job.status == JobStatus.PREEMPTED:
                try:
                    job.load_frontier()
                except CheckpointCorrupt as e:
                    # A torn preemption spill loses ONLY this job's
                    # frontier — fail it alone instead of letting the
                    # exception escalate to the service-wide bail-out.
                    job.status = JobStatus.ERROR
                    job.error = f"preemption spill unreadable: {e}"
                    job.metrics.finished_at = time.monotonic()
                    self._events.emit(
                        "job.error", job=job.id, trace=job.trace,
                        error=job.error,
                    )
                    job.event.set()
                    self._idle.notify_all()
                    continue
                job.status = JobStatus.RUNNING
                job.steps_since_admit = 0
                self._engine.group_of(job).jobs.append(job)
                # Re-admission after a preempt: legal because the timeline
                # saw `job.preempted` in between (obs/timeline.py treats a
                # second admit WITHOUT one as the duplicate-admission
                # anomaly).
                self._events.emit(
                    "replica.admit", job=job.id, trace=job.trace,
                    preempted=True,
                )
                continue
            resumed = job.resume is not None
            try:
                with self._tracer.span(
                    "service.admit", cat="service", job=job.id,
                    trace=job.trace,
                ):
                    done = self._engine.admit(job)
            except ServiceError:
                raise
            except Exception as e:  # noqa: BLE001 — a bad model fails its job
                job.status = JobStatus.ERROR
                job.error = f"admission failed: {e}"
                job.metrics.finished_at = time.monotonic()
                self._events.emit(
                    "job.error", job=job.id, trace=job.trace, error=job.error
                )
                job.event.set()
                self._idle.notify_all()
                continue
            job.metrics.admitted_at = time.monotonic()
            qw = job.metrics.queue_wait()
            if qw is not None:
                # p99 admission-latency sample (autoscaler signal).
                self._queue_waits.append(qw)
                self._adm_hist.observe(qw * 1000.0)
            job.status = JobStatus.RUNNING
            job.steps_since_admit = 0
            # `job.resumed` (a fleet requeue continuing from its journal
            # checkpoint) vs a first admission — the timeline's crash →
            # requeue → resume hop is exactly this pair of spellings.
            self._events.emit(
                "job.resumed" if resumed else "replica.admit",
                job=job.id, trace=job.trace,
            )
            if done is not None:
                self._finalize(job)

    def _preempt_if_due(self) -> None:
        """Park the longest-running over-budget job (at most one per round)
        when waiting jobs cannot be admitted — round-robin lane grants at
        admission-queue scale."""
        if self.preempt_steps is None or not len(self._adm):
            return
        if self._admittable():
            return  # free capacity: nothing to preempt for
        head = self._adm.peek()
        due = [
            j for j in self._resident()
            if j.steps_since_admit >= self.preempt_steps
            # Never preempt for a strictly lower-priority waiter — that
            # would just swap the pair back and forth round after round.
            and head.priority >= j.priority
        ]
        if not due:
            return
        job = max(due, key=lambda j: j.steps_since_admit)
        self._tracer.instant(
            "service.preempt", cat="service", job=job.id, trace=job.trace
        )
        self._events.emit("job.preempted", job=job.id, trace=job.trace)
        g = self._engine.groups.get(id(job.model))
        if g is not None and job in g.jobs:
            g.jobs.remove(job)
        job.status = JobStatus.PREEMPTED
        job.metrics.preemptions += 1
        # Partial-publish the preemption cut (corpus v2) BEFORE the spill
        # drops the in-memory frontier: if this replica dies while the job
        # is parked, a successor process warm-starts from the published
        # prefix instead of cold. The npz write drains off-lock with the
        # round's other deferred completion work.
        payload = self._engine.prepare_publish(job)
        if payload is not None:
            self._publishing.append(payload)
        if self.spill_dir is not None and job.pending_lanes:
            job.spill_frontier(
                os.path.join(self.spill_dir, f"job{job.id}.frontier.npz")
            )
        self._adm.push(job)
        self._admit_waiting()

    def _handle_step_fault(self, fault: StepFault) -> None:
        """Per-group retry, then solo-probe quarantine. The faulted lanes
        were already pushed back (scheduler.step_group's unwind), so:

        1. within the retry budget, just let the next round re-step the
           group — the retry is exact;
        2. past the budget, probe each of the group's runnable jobs SOLO:
           a job whose step fails in isolation is the poison — quarantine
           it; healthy jobs keep their (exactly preserved) progress and
           resume shared batching. Unrelated groups never notice."""
        group = fault.group
        group.fault_count += 1
        if group.fault_count <= self.retry_limit:
            self._engine.fault_counters["retries"] += 1
            self._tracer.instant(
                "service.step_retry", cat="service",
                attempt=group.fault_count,
            )
            return
        group.fault_count = 0
        for job in list(group.runnable()):
            try:
                finished = self._engine.step_group(group, only=[job])
            except StepFault as probe:
                self._quarantine(job, probe)
            except ServiceError:
                raise
            else:
                for j in finished:
                    self._finalize(j)

    def _quarantine(self, job: Job, fault: StepFault) -> None:
        """Park a poison job as an ERROR with the quarantined marker; its
        table entries stay (salted — they shadow nothing) and its lanes
        free up at the next round."""
        self._tracer.instant(
            "service.quarantine", cat="service", job=job.id, trace=job.trace
        )
        job.quarantined = True
        job.status = JobStatus.ERROR
        job.error = (
            f"quarantined after repeated step faults: {fault.cause!r}"
        )
        job.metrics.finished_at = time.monotonic()
        self._engine.retire(job)
        self._engine.fault_counters["quarantined_jobs"] += 1
        self._events.emit(
            "job.quarantined", job=job.id, trace=job.trace, error=job.error
        )
        job.event.set()
        self._idle.notify_all()

    def _round(self) -> bool:
        """One scheduling round: timeouts, admission, preemption, one fused
        step of the next runnable group. Returns True if a step ran. A
        `StepFault` is absorbed here (retry/quarantine policy) — one bad
        group or job never takes the scheduler down."""
        plan = active_plan()
        if (
            plan is not None
            and self._events.enabled
            and (plan.events is None or plan.events.closed)
        ):
            # The flight recorder adopts the active chaos plan: every
            # injected fault is journaled as `fault.injected`, so a chaos
            # run is an auditable recording, not just a survived one. A
            # plan outliving a previous recorded run (its journal closed)
            # is re-adopted here rather than emitting into the dead one.
            plan.events = self._events
        self._expire_timeouts()
        self._admit_waiting()
        self._preempt_if_due()
        group = self._engine.next_group()
        if group is None:
            return False
        try:
            finished = self._engine.step_group(group)
        except StepFault as e:
            self._handle_step_fault(e)
            return True
        # Lane-occupancy sample per fused step (the distribution behind
        # the `/.status` `lane_util` point value).
        self._lane_hist.observe(self._engine.lane_util())
        for job in finished:
            self._finalize(job)
        return True

    def _loop(self) -> None:
        try:
            while True:
                with self._work:
                    while (
                        not self._closed
                        and not self._has_work()
                        and not self._finalizing
                    ):
                        # The wait doubles as the timeout poll for deadlines.
                        self._work.wait(timeout=0.05)
                        self._expire_timeouts()
                    if self._closed:
                        return
                    try:
                        self._round()
                    except ServiceError as e:
                        self._failed = str(e)
                        self._idle.notify_all()
                        return
                    except Exception as e:  # noqa: BLE001 — never die silently
                        # A scheduler bug outside the StepFault envelope
                        # used to kill this thread silently, hanging every
                        # client in result(); fail loudly instead.
                        self._failed = (
                            f"scheduler error: {type(e).__name__}: {e}"
                        )
                        self._engine._fail_all(self._failed)
                        self._idle.notify_all()
                        return
                # Off-lock: the expensive completion half (corpus publish)
                # of any jobs this round finished — polls proceed meanwhile.
                self._drain_finalizers()
        finally:
            self._drain_finalizers()  # error exits still complete waiters

    # -- foreground driving (background=False) ---------------------------------

    def pump(self, rounds: int = 1) -> int:
        """Run up to `rounds` scheduling rounds in the calling thread;
        returns how many actually dispatched a step. Deferred completion
        work (the off-lock corpus publish half) drains after the lock is
        released — a pump always leaves finished jobs fully completed."""
        ran = 0
        try:
            with self._lock:
                for _ in range(rounds):
                    try:
                        if self._round():
                            ran += 1
                        elif not self._has_work():
                            break
                    except ServiceError as e:
                        self._failed = str(e)
                        raise
        finally:
            self._drain_finalizers()
        return ran

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted job has finished."""
        deadline = None if timeout is None else time.monotonic() + timeout

        def all_done():
            with self._lock:
                return all(
                    j.status in JobStatus.FINISHED and j.event.is_set()
                    for j in self._jobs.values()
                )

        if self._thread is None:
            # Foreground: pump WITHOUT holding the lock across rounds
            # (pump takes it per burst and drains the off-lock completion
            # work between bursts — the no-stall contract applies to
            # foreground services too).
            while not all_done():
                if self._failed:
                    raise ServiceError(self._failed)
                if not self.pump(64):
                    with self._lock:
                        self._expire_timeouts()
                        idle_now = not self._has_work()
                    self._drain_finalizers()
                    if not all_done() and idle_now:
                        time.sleep(0.01)
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("drain timed out")
            return
        with self._idle:
            while not all_done():
                if self._failed:
                    raise ServiceError(self._failed)
                left = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if left is not None and left <= 0:
                    raise TimeoutError("drain timed out")
                self._idle.wait(timeout=0.05 if left is None else min(left, 0.05))

    def close(self) -> None:
        """Stop the scheduler thread; queued/running jobs are cancelled."""
        with self._work:
            self._closed = True
            for job in list(self._jobs.values()):
                if job.status not in JobStatus.FINISHED:
                    self._adm.remove(job)
                    job.status = JobStatus.CANCELLED
                    # Shutdown cut: publish the visited prefix so a fresh
                    # process resumes warm (drained below, off-lock).
                    payload = self._engine.prepare_publish(job)
                    if payload is not None:
                        self._publishing.append(payload)
                    self._engine.retire(job)
                    self._events.emit(
                        "job.cancelled", job=job.id, trace=job.trace,
                        shutdown=True,
                    )
                    job.event.set()
            self._work.notify_all()
            self._idle.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # Any finalize still deferred (scheduler died mid-drain, or a
        # foreground service closed between pumps) must complete its
        # waiters — result() clients hang on job events otherwise.
        self._drain_finalizers()
        REGISTRY.unregister(self._metrics_name)
        if self._trace_out:
            try:
                self._tracer.save(self._trace_out)
            except OSError:
                pass  # tracing must never fail a clean shutdown
        # Release a chaos plan that adopted this recorder — the plan may
        # outlive us, and its next journaled run must re-adopt a LIVE one.
        plan = active_plan()
        if plan is not None and plan.events is self._events:
            plan.events = None
        # The recorder outlives the service only when it was handed in
        # (the fleet owns its per-replica journals); an owned one closes.
        if self._events_owned is not None:
            self._events_owned.close()
        else:
            self._events.flush()


class ServiceChecker(Checker):
    """`Checker`-shaped adapter over a JobHandle — the same handle surface
    `spawn_tpu` gives (counts, discoveries, join, assertions), served by a
    shared CheckService instead of a dedicated engine. Spawn one via
    `model.checker().spawn_service(service)`."""

    def __init__(self, handle: JobHandle):
        super().__init__(handle._job.model)
        self._handle = handle

    def state_count(self) -> int:
        return self._handle._job.state_count

    def unique_state_count(self) -> int:
        return self._handle._job.unique_count

    def max_depth(self) -> int:
        return self._handle._job.max_depth

    def discoveries(self) -> dict:
        if not self._handle._job.event.is_set():
            return {}
        return self._handle.discoveries()

    def join(self) -> "ServiceChecker":
        self._handle.result(wait=True)
        return self

    def is_done(self) -> bool:
        return self._handle._job.event.is_set()

    def store_stats(self) -> Optional[dict]:
        return self._handle._service.store_stats()

    def table_fill(self) -> Optional[float]:
        return self._handle._service.table_fill()

    def drift_ratio(self) -> Optional[float]:
        return self._handle._service.drift_ratio()

    def telemetry_summary(self) -> Optional[dict]:
        return self._handle._service.telemetry_summary()
