"""Per-tenant identity, quotas, and fair-share accounting.

The r13 service and r19 fleet treat every client as one anonymous stream:
one admission queue, one waterfill over jobs, one corpus namespace. This
module is the tenancy half of the elastic control plane — the identity a
submission carries (`tenant=`, threaded `FleetRouter.submit` →
`CheckService.submit` → `Job`) and the admission-time quota gate that
keeps one tenant's flood from consuming the device:

- **in-flight quota** — a hard cap on a tenant's unfinished jobs,
  enforced by a live scan of the job table (no release bookkeeping to
  leak: a job that finishes, errors, or is cancelled simply stops
  counting).
- **lane-seconds budget** — a replenishing budget of device share
  (lanes x wall-seconds of fused steps the tenant's jobs held lanes in,
  charged by the scheduler AFTER each successful step). The budget
  refills linearly over `window_s`, so a tenant that burns its burst is
  throttled to a sustained rate rather than banned.

Both violations surface as :class:`QuotaExceeded`, which the HTTP front
ends (`service/server.py`, `service/router.py serve_fleet`) convert to a
**429 with a Retry-After header** — the same retry contract as the r13
503 path, so well-behaved clients need exactly one backoff loop.

The **default tenant is free**: ``tenant="default"`` carries no quota, no
corpus salt, and no result-detail sub-dict, so every pre-tenancy golden
(and every caller that never heard of tenants) is byte-identical.

Scheduling fairness does NOT live here — the two-level waterfill (tenants
first, then a tenant's jobs) is the scheduler's, and tenant-fair
admission rotation is the queue's; this module only decides *admission*
and *accounting*.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

#: The quota-free namespace every tenant-less caller lands in.
DEFAULT_TENANT = "default"


class QuotaExceeded(Exception):
    """A tenant's submission was refused at admission time.

    Carries the machine-readable pieces the HTTP layer needs: the tenant,
    which quota tripped (``in_flight`` | ``lane_seconds``), and a
    suggested ``retry_after_s`` (for the lane-seconds budget this is the
    linear-refill time until the tenant is under budget again, so an
    honest client's single sleep usually succeeds)."""

    def __init__(self, tenant: str, reason: str, retry_after_s: float = 1.0):
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = max(retry_after_s, 0.1)
        super().__init__(
            f"tenant {tenant!r} over quota ({reason}); "
            f"retry after {self.retry_after_s:.1f}s"
        )


@dataclass
class TenantQuota:
    """Limits for one tenant; ``None`` means unlimited on that axis."""

    max_in_flight: Optional[int] = None
    #: lane-seconds the tenant may hold "in the bucket" (burst budget).
    lane_seconds: Optional[float] = None
    #: seconds over which a fully-spent budget refills to zero spend —
    #: the sustained rate is ``lane_seconds / window_s``.
    window_s: float = 60.0


class TenantQuotas:
    """Thread-safe quota table + lane-seconds ledger.

    One instance is shared by the admission gate (``admit``), the
    scheduler's post-step charging (``charge``), and the stats surface
    (``snapshot``). Tenants without a configured quota pass ``admit``
    unconditionally — the ledger still records their spend so operators
    can see who is using the device before deciding to fence them."""

    def __init__(self, quotas: Optional[Dict[str, TenantQuota]] = None):
        self._lock = threading.Lock()
        self._quotas: Dict[str, TenantQuota] = dict(quotas or {})
        self._spent: Dict[str, float] = {}
        self._last_refill: Dict[str, float] = {}

    def set_quota(
        self,
        tenant: str,
        max_in_flight: Optional[int] = None,
        lane_seconds: Optional[float] = None,
        window_s: float = 60.0,
    ) -> None:
        with self._lock:
            self._quotas[tenant] = TenantQuota(
                max_in_flight=max_in_flight,
                lane_seconds=lane_seconds,
                window_s=window_s,
            )

    def quota(self, tenant: str) -> Optional[TenantQuota]:
        with self._lock:
            return self._quotas.get(tenant)

    # -- lane-seconds ledger -------------------------------------------

    def _refill_locked(self, tenant: str, now: float) -> None:
        q = self._quotas.get(tenant)
        last = self._last_refill.get(tenant)
        self._last_refill[tenant] = now
        if last is None or tenant not in self._spent:
            return
        if q is None or not q.lane_seconds or q.window_s <= 0:
            return
        rate = q.lane_seconds / q.window_s
        self._spent[tenant] = max(
            0.0, self._spent[tenant] - rate * (now - last)
        )

    def charge(self, tenant: str, lane_seconds: float) -> None:
        """Record device share consumed (scheduler, AFTER a successful
        fused step — a faulted step that unwound its metrics never
        reaches here, so the ledger cannot double-charge a retry)."""
        if lane_seconds <= 0:
            return
        now = time.monotonic()
        with self._lock:
            self._refill_locked(tenant, now)
            self._spent[tenant] = self._spent.get(tenant, 0.0) + lane_seconds

    def spent(self, tenant: str) -> float:
        now = time.monotonic()
        with self._lock:
            self._refill_locked(tenant, now)
            return self._spent.get(tenant, 0.0)

    # -- admission gate ------------------------------------------------

    def admit(self, tenant: str, in_flight: int) -> None:
        """Raise :class:`QuotaExceeded` if `tenant` may not submit now.

        `in_flight` is the caller's live count of the tenant's unfinished
        jobs (the router counts fleet-wide, the standalone service counts
        its own table). The default tenant is never gated."""
        if tenant == DEFAULT_TENANT:
            return
        now = time.monotonic()
        with self._lock:
            q = self._quotas.get(tenant)
            if q is None:
                return
            if q.max_in_flight is not None and in_flight >= q.max_in_flight:
                raise QuotaExceeded(
                    tenant,
                    f"in_flight {in_flight} >= max {q.max_in_flight}",
                    retry_after_s=1.0,
                )
            if q.lane_seconds:
                self._refill_locked(tenant, now)
                spent = self._spent.get(tenant, 0.0)
                if spent >= q.lane_seconds:
                    rate = q.lane_seconds / max(q.window_s, 1e-9)
                    wait = (spent - q.lane_seconds) / rate + 0.1
                    raise QuotaExceeded(
                        tenant,
                        f"lane_seconds {spent:.2f} >= budget "
                        f"{q.lane_seconds:.2f}",
                        retry_after_s=min(wait, 30.0),
                    )

    def snapshot(self) -> dict:
        """Per-tenant {max_in_flight, lane_seconds, window_s, spent} for
        the stats/`.status` surfaces."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for tenant in set(self._quotas) | set(self._spent):
                self._refill_locked(tenant, now)
                q = self._quotas.get(tenant)
                out[tenant] = {
                    "max_in_flight": q.max_in_flight if q else None,
                    "lane_seconds": q.lane_seconds if q else None,
                    "window_s": q.window_s if q else None,
                    "spent": round(self._spent.get(tenant, 0.0), 6),
                }
            return out


def tenant_salt(tenant: Optional[str]) -> Optional[str]:
    """The corpus-namespace salt for `tenant` — ``None`` for the default
    tenant (and for ``None``), so default-namespace content keys are
    byte-identical to the pre-tenancy corpus and existing entries keep
    serving."""
    if not tenant or tenant == DEFAULT_TENANT:
        return None
    return tenant
