"""Replica drivers + the ServiceFleet assembly (router front door over N
CheckService replicas).

A `Replica` wraps one CheckService (one device / device-mesh worth of
shared table) in a crash-only driver: it pumps the service's scheduling
rounds, checkpoints its journaled jobs through the r10 atomic checkpoint
plane (faults/ckptio.py — every write leaves a verified `.prev`
generation), and DIES on the first unhandled fault — including the
injected `fleet.replica_crash` chaos kind and the service-wide
`ServiceError` class the single-service deployment could only abort on.
Recovery is never the replica's business: the `FleetRouter`
(service/router.py) detects the death through its health probes and
requeues the replica's jobs onto survivors from their newest intact
checkpoint generation.

`ServiceFleet` is the assembly: N replicas + one router + (background
mode) one driver thread per replica and one router supervision thread.
Foreground mode (`background=False`) runs no threads at all — tests drive
the whole fleet deterministically with `pump()` / `drain()`, the same
discipline CheckService itself uses.

    fleet = ServiceFleet(n_replicas=3, service_kwargs=dict(
        batch_size=4096, table_log2=22))
    h = fleet.submit(model, timeout=600)
    r = h.result()          # survives any single replica's death
    serve_fleet(fleet)      # HTTP front door: POST /jobs, /.status, /metrics
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Callable, Optional

from ..faults.blobstore import is_blob_uri, normalize_root
from ..faults.ckptio import fenced_savez
from ..faults.plan import FaultError, maybe_fault
from ..obs import EventJournal, as_events, as_tracer
from .api import CheckService
from .lease import (
    FencedEvents,
    LeaseRevoked,
    LeaseStore,
    load_fenced_resume,
)
from .queue import JobResume, JobStatus
from .router import (  # noqa: F401
    FleetJobStatus,
    FleetRouter,
    ReplicaDead,
    ResumeToken,
    lease_member,
    serve_fleet,
)

__all__ = ["Replica", "ServiceFleet", "serve_fleet"]


class Replica:
    """One CheckService behind a crash-only driver. The service always runs
    foreground (`background=False`) — THIS object owns the pumping, so the
    chaos plane has one seam (`fleet.replica_crash`) through which to kill
    the whole replica, and the fleet's foreground mode can drive it
    deterministically."""

    def __init__(
        self,
        idx: int,
        service_factory: Callable[[], CheckService],
        ckpt_every_spins: int = 1,
        pump_rounds: int = 4,
        tracer=None,
        events=None,
        lease=None,
    ):
        self.idx = idx
        self.service = service_factory()
        self.ckpt_every_spins = ckpt_every_spins
        self.pump_rounds = pump_rounds
        # Epoch fence (service/lease.py): every checkpoint generation this
        # driver writes is stamped + re-validated against this lease; a
        # revoked replica (the router declared it dead — possibly wrongly,
        # the zombie case) refuses its own writes and dies instead of
        # publishing stale generations for requeued jobs.
        self.lease = lease
        if lease is not None:
            corpus = getattr(self.service._engine, "_corpus", None)
            if corpus is not None:
                # The corpus write path is fenced with the same token:
                # zombie publishes refuse themselves and stale entries are
                # stamp-rejected at lookup.
                corpus.set_lease(lease)
        self.error: Optional[str] = None
        self._dead = False
        self._spins = 0
        self._ckpt_paths: dict[int, str] = {}  # inner job id -> ckpt path
        self._tracer = as_tracer(tracer)
        # Flight-recorder journal (obs/events.py) shared with this
        # replica's CheckService: the driver adds the durability events
        # (`ckpt.write`) and flushes on death so a crash's journal tail
        # survives for the forensic pass.
        self._events = as_events(events)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Condition()

    # -- router-facing surface -------------------------------------------------

    @property
    def alive(self) -> bool:
        return not self._dead

    def submit(self, spec: dict, ckpt_path: Optional[str] = None):
        """Submit one job spec (CheckService.submit kwargs + journal/
        resume) to this replica; registers its checkpoint path with the
        driver. Raises ReplicaDead instead of touching a dead service.

        A `ResumeToken` resume is resolved HERE (the replica seam's side
        of the contract): the newest fenced checkpoint generation is
        loaded in this process — stale (revoked-epoch) generations from a
        zombie writer are rejected by the stamp check and the fallback
        generation serves instead."""
        if self._dead:
            raise ReplicaDead(
                f"replica {self.idx} is dead ({self.error})"
            )
        spec = dict(spec)
        spec.pop("model_ref", None)  # in-proc: the model object itself rides
        resume = spec.get("resume")
        if isinstance(resume, ResumeToken):
            spec["resume"] = self._resolve_resume(resume)
        handle = self.service.submit(**spec)
        if ckpt_path is not None:
            self._ckpt_paths[handle.id] = ckpt_path
        with self._wake:
            self._wake.notify_all()
        return handle

    def _resolve_resume(self, token: ResumeToken) -> Optional[JobResume]:
        """ResumeToken -> JobResume through the fenced loader; None (fresh
        restart, still exact) when no generation passes CRC + fence."""
        return load_fenced_resume(
            token.path, self.lease.store if self.lease is not None else None
        )

    def withdraw(self, inner_job_id: int) -> bool:
        """Work-stealing primitive: atomically remove a still-QUEUED job
        (see CheckService.withdraw)."""
        if self._dead:
            return False
        return self.service.withdraw(inner_job_id)

    def probe(self) -> dict:
        """Health probe (the router's `/.status`-plane check): raises on a
        dead replica, answers cheap live counters otherwise. Deliberately
        lock-free — a replica mid-compile must read as healthy, and a
        truly wedged one is caught by the router's probe deadline (the
        `fleet.replica_hang` chaos point parks right here). The
        `fleet.partition` point fires here too (and in every RemoteReplica
        HTTP request): an injected partition makes this replica
        unreachable from the router while the replica itself keeps
        running — the false-positive death the lease fence covers."""
        maybe_fault("fleet.partition", replica=self.idx)
        maybe_fault("fleet.replica_hang", replica=self.idx)
        if self._dead:
            raise ReplicaDead(
                f"replica {self.idx} is dead ({self.error})"
            )
        failed = self.service._failed
        if failed:
            raise ReplicaDead(f"replica {self.idx} service failed: {failed}")
        return {
            "replica": self.idx,
            "queued": len(self.service._adm),
            "device_steps": self.service._engine.total_steps,
            **self._signal_row(),
        }

    def _signal_row(self) -> dict:
        """The autoscaler's per-replica signal pair (lane utilization,
        p99 admission wait), read LOCK-FREE like the rest of the probe
        plane: a replica mid-compile holds the service lock and must
        still report. The racy deque snapshot degrades to empty — a
        missing sample, never a wedged probe."""
        svc = self.service
        try:
            waits = sorted(svc._queue_waits)
        except RuntimeError:  # srlint: fault-ok racy deque snapshot
            waits = []
        p99 = 0.0
        if waits:
            p99 = round(
                waits[min(len(waits) - 1, int(0.99 * len(waits)))] * 1e3, 3
            )
        row = {
            "lane_util": round(svc._engine.lane_util(), 4),
            "adm_p99_ms": p99,
        }
        # Measured/predicted cost ratio from the calibration comparator
        # (obs/calib.py) — same lock-free discipline: a plain attribute
        # read of the last closed chunk, absent until one closes.
        calib = svc._engine._calib
        if calib is not None:
            ratio = calib.drift_ratio()
            if ratio is not None:
                row["drift"] = round(ratio, 3)
        return row

    def idle(self) -> bool:
        """True iff this replica has nothing queued and nothing runnable —
        the steal-eligibility test (mirrors CheckService._has_work without
        taking the service lock)."""
        if self._dead:
            return False
        svc = self.service
        if len(svc._adm):
            return False
        try:
            return not any(
                g.runnable() for g in svc._engine.groups.values()
            )
        except RuntimeError:  # srlint: fault-ok racy dict walk reads as busy
            return False

    def snapshot_row(self) -> dict:
        """One `/.status` row. Dead replicas report liveness only — crash
        semantics say their service state is gone."""
        if self._dead:
            return {"alive": 0, "error": self.error}
        svc = self.service
        return {
            "alive": 1,
            "queued": len(svc._adm),
            "jobs": len(svc._jobs),
            "device_steps": svc._engine.total_steps,
            "spins": self._spins,
            # Per-replica autoscaler signals, also the `/.status` +
            # `/metrics` per-replica depth/utilization surface.
            **self._signal_row(),
        }

    # -- the crash-only driver -------------------------------------------------

    def spin(self) -> int:
        """One driver turn: the chaos seam, a bounded pump, and the
        checkpoint cadence. Returns rounds that dispatched work; a fault
        anywhere kills the replica (recovery is the router's job)."""
        if self._dead:
            return 0
        try:
            # Chaos-plane boundary: `fleet.replica_crash` (kind `crash`)
            # kills this replica for good — BEFORE the pump, so the last
            # written checkpoint generation is a sound resume point.
            maybe_fault("fleet.replica_crash", replica=self.idx)
            ran = self.service.pump(self.pump_rounds)
            self._spins += 1
            if self._spins % self.ckpt_every_spins == 0:
                self._checkpoint_jobs()
            return ran
        except Exception as e:  # noqa: BLE001 — crash-only: die, never limp
            self._die(e)
            return 0

    def _die(self, e: BaseException) -> None:
        self._dead = True
        self.error = f"{type(e).__name__}: {e}"
        self._tracer.instant(
            "fleet.replica_crash", cat="fleet", replica=self.idx,
            error=type(e).__name__,
        )
        # Crash-durability: push the journal tail and the partial trace to
        # disk NOW — this driver never runs again, and the flight recorder
        # exists exactly for this moment. (The `replica.crash` journal
        # event itself is the router's to write: it is the single
        # authority on fleet membership, so event counts match its
        # `replica_crashes` counter.)
        self._events.flush()
        self._tracer.flush()

    def _checkpoint_jobs(self) -> None:
        """Write one atomic generation per RUNNING journaled job. The
        snapshot is taken under the service lock (no step mutates
        mid-copy); the write happens outside it."""
        for jid, path in list(self._ckpt_paths.items()):
            job = self.service._jobs.get(jid)
            if job is None or job.status in JobStatus.FINISHED:
                self._ckpt_paths.pop(jid, None)
                continue
            if job.status != JobStatus.RUNNING or job.journal is None:
                continue
            with self.service._lock:
                arrays = job.fleet_snapshot()
            try:
                with self._tracer.span(
                    "ckpt.write", cat="fleet", job=jid, replica=self.idx,
                    trace=job.trace,
                ):
                    fenced_savez(path, arrays, lease=self.lease)
            except LeaseRevoked as e:
                # The router fenced this replica out (it declared us dead
                # and requeued our jobs — we are the zombie). The refusal
                # was counted by the lease store; record the evidence and
                # die: a fenced-out replica must never write again, and
                # crash-only semantics say it must not limp either.
                self._events.emit(
                    "lease.reject", member=lease_member(self.idx),
                    epoch=self.lease.epoch if self.lease else 0,
                    surface="write", job=jid, trace=job.trace,
                )
                self._die(e)
                return
            self._events.emit(
                "ckpt.write", job=jid, trace=job.trace, replica=self.idx
            )

    def _drive(self) -> None:
        while not self._stop and not self._dead:
            ran = self.spin()
            if not ran and not self._stop:
                with self._wake:
                    self._wake.wait(timeout=0.002)

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._drive, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop = True
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        self.stop()
        if not self._dead:
            self.service.close()

    def retire_driver(self) -> None:
        """Graceful local teardown AFTER the router's scale-in drain
        (`FleetRouter.retire` already revoked the lease and requeued
        every fleet job): stop pumping, close the service, and read as
        not-alive from here on — without the crash narrative `_die`
        writes, because retirement is a decision, not a failure."""
        self.stop()
        if self._dead:
            return
        try:
            self.service.close()
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        self._dead = True
        self.error = "retired (scale-in)"


class ServiceFleet:
    """N CheckService replicas behind one consistent-hash router — the
    production deployment of the check service (ROADMAP item 1): replica
    death is routine (requeue-resume from the checkpoint plane), imbalance
    is routine (cross-replica work stealing), and the whole fleet reports
    through one `/.status` + `/metrics` plane."""

    def __init__(
        self,
        n_replicas: int = 2,
        service_kwargs: Optional[dict] = None,
        router_kwargs: Optional[dict] = None,
        ckpt_dir: Optional[str] = None,
        ckpt_every_spins: int = 1,
        pump_rounds: int = 4,
        max_resident: Optional[int] = 8,
        background: bool = True,
        tracer=None,
        journal_dir: Optional[str] = None,
        corpus_dir: Optional[str] = None,
        lease_dir: Optional[str] = None,
        remote: bool = False,
        store_root: Optional[str] = None,
        spawn_timeout_s: float = 180.0,
        quotas=None,
    ):
        """`service_kwargs` configure every replica's CheckService
        (batch_size, table_log2, store, ...). `max_resident` bounds each
        replica's admitted jobs so overload is visible as queue depth —
        what work stealing feeds on (None disables the bound AND
        stealing's signal). `ckpt_dir` (default: a managed tempdir) holds
        the per-job requeue-resume generations.

        `journal_dir` turns on the flight recorder (obs/events.py): the
        router journals to `<journal_dir>/router.jsonl` and each replica
        (driver + its CheckService) to `<journal_dir>/replica<i>.jsonl`,
        all keyed by the per-job trace id the router mints — the input
        set for `python -m stateright_tpu.obs.timeline`.

        `corpus_dir` turns on the cross-job warm-start corpus on EVERY
        replica over the one shared directory (store/corpus.py): the
        first replica to finish a content key publishes its visited set
        as a content-addressed ckptio generation, every replica's next
        same-key submission — fresh, requeued after a crash, or stolen —
        preloads that shared generation instead of re-deriving it.
        Implies `store="tiered"` on the replica services (set here as a
        default when service_kwargs doesn't choose a store).

        `lease_dir` turns on the epoch-fenced lease plane (service/
        lease.py) for IN-PROC replicas: the router grants one lease per
        replica, revokes it before requeueing a dead replica's jobs, and
        every replica write path (checkpoint generations, terminal journal
        events) re-validates its lease — a false-positive death (hung but
        alive) can waste cycles but can never corrupt a resumed job.

        `remote=True` runs every replica as a separate PROCESS: N
        `replica_main` subprocesses (each its own `serve_service`-shaped
        HTTP server over a `Replica` driver) sharing `store_root`
        (checkpoints, journals, leases, corpus), driven through
        `RemoteReplica` HTTP stubs behind the same router. The lease plane
        and the flight recorder are always on in remote mode — they are
        what makes cross-process death declarations sound. Requires
        `background=True` (subprocesses cannot be foreground-pumped).

        `store_root` (and every *_dir) may be a ``blob://host:port[/pfx]``
        URI (faults/blobstore.py): checkpoint generations, lease records,
        corpus entries, and member-discovery records then live in the
        object store — the TRUE multi-host root, where the root URI is
        the only configuration replicas share. Journals stay local-write
        (a scratch directory) and are blob-synced at flush boundaries;
        replica addresses are discovered from ``members/`` records in the
        root (service/discovery.py) instead of hand-wired port files.

        `quotas` (service/tenancy.py `TenantQuotas`) arms the tenancy
        plane fleet-wide: the ROUTER is the single admission gate
        (per-tenant in-flight cap + lane-seconds budget → 429 with
        Retry-After over HTTP), and every in-proc replica shares the
        same ledger for lane-seconds charging with its own gate OFF
        (`quota_gate=False` — a requeued job must never bounce off a
        budget its first admission already passed). Remote replicas
        cannot share the in-memory ledger across processes, so remote
        fleets gate on the in-flight cap only."""
        if n_replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        self._tracer = as_tracer(tracer)
        self._tracer_raw = tracer
        self._tmpdir = None
        self._scratch_tmp = None
        self.remote = bool(remote)
        store_root = normalize_root(store_root)
        ckpt_dir = normalize_root(ckpt_dir)
        journal_dir = normalize_root(journal_dir)
        lease_dir = normalize_root(lease_dir)
        corpus_dir = normalize_root(corpus_dir)
        self.store_root = store_root
        self.scratch_dir: Optional[str] = None
        if remote:
            if not background:
                raise ValueError(
                    "remote fleets are background-only (subprocess replicas "
                    "cannot be foreground-pumped)"
                )
            if store_root is None:
                self._tmpdir = tempfile.TemporaryDirectory(
                    prefix="srtpu-fleet-root-"
                )
                self.store_root = store_root = self._tmpdir.name
            if is_blob_uri(store_root):
                # A blob root holds the shared durable state; local-write
                # surfaces (journals, child logs) need a scratch directory
                # on THIS host, synced/irrelevant-to the blob root.
                self._scratch_tmp = tempfile.TemporaryDirectory(
                    prefix="srtpu-fleet-scratch-"
                )
                self.scratch_dir = self._scratch_tmp.name
            else:
                os.makedirs(store_root, exist_ok=True)
                self.scratch_dir = store_root
            ckpt_dir = ckpt_dir or os.path.join(store_root, "ckpt")
            journal_dir = journal_dir or os.path.join(store_root, "journal")
            lease_dir = lease_dir or os.path.join(store_root, "leases")
            if corpus_dir is None and "corpus_dir" in (service_kwargs or {}):
                corpus_dir = (service_kwargs or {}).get("corpus_dir")
        if ckpt_dir is None:
            self._tmpdir = tempfile.TemporaryDirectory(prefix="srtpu-fleet-")
            ckpt_dir = self._tmpdir.name
        if not is_blob_uri(ckpt_dir):
            os.makedirs(ckpt_dir, exist_ok=True)
        self.journal_dir = journal_dir
        self._journals: list = []
        router_journal = None
        if journal_dir is not None:
            jpath, jsync = self._journal_path("router.jsonl")
            router_journal = EventJournal(
                jpath, writer="router", sync_uri=jsync
            )
            self._journals.append(router_journal)
        # Lease plane: grants happen HERE, before any replica starts (a
        # remote member ACQUIRES the granted lease at boot; an in-proc one
        # is handed its Lease directly).
        self.lease_store = None
        router_lease = None
        if lease_dir is not None:
            self.lease_store = LeaseStore(lease_dir)
            router_lease = self.lease_store.grant("router")
        kw = dict(service_kwargs or {})
        kw.setdefault("max_resident", max_resident)
        self.quotas = quotas
        if quotas is not None and not remote:
            # Shared lane-seconds ledger on every in-proc replica —
            # charging only; the router is the single admission gate.
            kw["quotas"] = quotas
            kw["quota_gate"] = False
        if corpus_dir is not None:
            if not is_blob_uri(corpus_dir):
                os.makedirs(corpus_dir, exist_ok=True)
            kw["corpus_dir"] = corpus_dir
            kw.setdefault("store", "tiered")
        self.corpus_dir = corpus_dir
        kw["background"] = False  # the Replica driver owns the pumping
        self._service_kw = kw
        self._ckpt_every_spins = ckpt_every_spins
        self._pump_rounds = pump_rounds
        self._spawn_timeout_s = spawn_timeout_s
        self._retired: list = []  # dead incarnations replaced by rejoins
        self._incarnations: dict = {}  # idx -> rejoin count (lease-less)
        # Serializes rejoin_replica end-to-end: deadness is monotonic
        # except through rejoin, so holding this across check+grant+spawn
        # +rejoin means a lost race can never burn a fresh epoch that
        # would implicitly fence the WINNING incarnation (grant bumps the
        # member's epoch, revoking older ones).
        self._rejoin_lock = threading.Lock()

        self._procs: list = []
        if remote:
            from .remote import RemoteReplica, spawn_replica_proc

            self.replicas = []
            try:
                for i in range(n_replicas):
                    self.lease_store.grant(lease_member(i))
                    proc, url = spawn_replica_proc(
                        i, store_root, kw, timeout_s=spawn_timeout_s,
                        scratch=self.scratch_dir,
                    )
                    self._procs.append(proc)
                    self.replicas.append(
                        RemoteReplica(
                            i, url, proc=proc, tracer=tracer,
                            store_root=store_root,
                        )
                    )
            except BaseException:
                # A mid-boot spawn failure must not leak the replicas that
                # DID come up (full jax processes) — nobody will ever call
                # close() on a constructor that raised.
                self._kill_procs()
                for j in self._journals:
                    j.close()
                if self.lease_store is not None:
                    self.lease_store.close()
                if self._scratch_tmp is not None:
                    self._scratch_tmp.cleanup()
                if self._tmpdir is not None:
                    self._tmpdir.cleanup()
                raise
        else:
            self.replicas = [
                self._make_inproc_replica(i) for i in range(n_replicas)
            ]
        self.router = FleetRouter(
            self.replicas,
            background=background,
            ckpt_dir=ckpt_dir,
            tracer=tracer,
            events=router_journal,
            lease_store=self.lease_store,
            router_lease=router_lease,
            quotas=quotas,
            **(router_kwargs or {}),
        )
        self.background = background
        self._closed = False
        self._router_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if background:
            for r in self.replicas:
                r.start()
            self._router_thread = threading.Thread(
                target=self._supervise, daemon=True
            )
            self._router_thread.start()

    # -- construction helpers --------------------------------------------------

    def _journal_path(self, name: str) -> tuple:
        """(local write path, blob sync URI or None) for one journal file:
        journals are always LOCAL-write (an emit must never pay a network
        round trip); on a blob journal root the local file lives in the
        scratch directory and mirrors to the root at flush boundaries."""
        jd = self.journal_dir
        if not is_blob_uri(jd):
            os.makedirs(jd, exist_ok=True)
            return os.path.join(jd, name), None
        if self.scratch_dir is None:
            self._scratch_tmp = tempfile.TemporaryDirectory(
                prefix="srtpu-fleet-scratch-"
            )
            self.scratch_dir = self._scratch_tmp.name
        local_dir = os.path.join(self.scratch_dir, "journal")
        os.makedirs(local_dir, exist_ok=True)
        return os.path.join(local_dir, name), os.path.join(jd, name)

    def _make_inproc_replica(self, i: int, rejoin: bool = False) -> Replica:
        """One in-proc Replica driver (fresh service, fresh lease epoch).
        A REJOINED incarnation journals to its own file under the writer
        name ``replica<i>@e<epoch>``: per-writer seq order stays monotonic
        across the restart (the merge contract), and the timeline fence
        tells the fenced old incarnation from this one by epoch."""
        member = lease_member(i)
        lease = (
            self.lease_store.grant(member)
            if self.lease_store is not None else None
        )
        writer, fname = member, f"replica{i}.jsonl"
        if rejoin:
            n = (
                lease.epoch if lease is not None
                else self._incarnations.get(i, 1) + 1
            )
            self._incarnations[i] = n
            writer, fname = f"{member}@e{n}", f"replica{i}.e{n}.jsonl"
        journal = None
        if self.journal_dir is not None:
            path, sync = self._journal_path(fname)
            journal = EventJournal(path, writer=writer, sync_uri=sync)
            self._journals.append(journal)
            if lease is not None:
                # Gate terminal/requeue-relevant events behind the
                # lease: a fenced-out replica's journal can no longer
                # record admissions/verdicts the timeline would trust.
                journal = FencedEvents(journal, lease)
        return Replica(
            i,
            lambda: CheckService(events=journal, **self._service_kw),
            ckpt_every_spins=self._ckpt_every_spins,
            pump_rounds=self._pump_rounds,
            tracer=self._tracer_raw,
            events=journal,
            lease=lease,
        )

    # -- replica rejoin --------------------------------------------------------

    def rejoin_replica(self, idx: int) -> bool:
        """Re-admit a dead/fenced member as a FRESH incarnation (ROADMAP
        item 1's rejoin residue): grant it a fresh lease epoch, rebuild
        the driver (in-proc) or respawn the subprocess (remote — it
        re-publishes its member-discovery record, so the router learns
        the new address from the store root alone), and hand it to
        `FleetRouter.rejoin`, which quarantines it behind probation
        probes before moving its keys back. Returns False when the member
        is still alive, or when the ``fleet.rejoin`` chaos point aborted
        the rejoin (the fresh incarnation is torn down; retry later).

        The fresh epoch is what makes a rejoin racing its own stale
        zombie safe: the moment the grant lands, the old incarnation's
        epoch fails the exact-epoch check on every fenced write/read —
        the zombie refuses itself, the rejoined member proceeds.

        Serialized (`_rejoin_lock`): two concurrent rejoins of one member
        must not both grant — the second grant would implicitly revoke
        the first incarnation's epoch and silently fence the winner."""
        with self._rejoin_lock:
            return self._rejoin_replica_locked(idx)

    def _rejoin_replica_locked(self, idx: int) -> bool:
        old = self.replicas[idx]
        if idx not in self.router._dead:
            # The ROUTER's verdict is the one that matters: only a
            # declared-dead member may rejoin (the old PROCESS may well
            # still be alive — the zombie case; its stale epoch is what
            # the fresh grant fences). A racing rejoin that already won
            # also lands here — and critically, nothing is GRANTED for a
            # member the router still considers a member.
            return False
        try:
            # Chaos boundary: BEFORE the grant and the spawn, so an
            # injected fault aborts the rejoin with nothing changed —
            # not even a burned lease epoch.
            maybe_fault("fleet.rejoin", replica=idx)
        except FaultError:
            return False
        member = lease_member(idx)
        proc = None
        if self.remote:
            from .remote import RemoteReplica, spawn_replica_proc

            lease = self.lease_store.grant(member)
            proc, url = spawn_replica_proc(
                idx, self.store_root, self._service_kw,
                timeout_s=self._spawn_timeout_s,
                scratch=self.scratch_dir,
                incarnation=lease.epoch,
            )
            new = RemoteReplica(
                idx, url, proc=proc, tracer=self._tracer_raw,
                store_root=self.store_root,
            )
        else:
            new = self._make_inproc_replica(idx, rejoin=True)
        if not self.router.rejoin(new):
            # Injected fleet.rejoin fault (or a racing recovery): tear the
            # fresh incarnation down — the member stays dead, nothing
            # leaks, and the caller retries on its own cadence.
            if proc is not None:
                self._kill_one(proc)
            else:
                new.close()
            return False
        self.replicas[idx] = new
        self._retired.append(old)
        if proc is not None:
            self._procs.append(proc)
        if self.background:
            new.start()
        if self.lease_store is not None:
            epoch, _state = self.lease_store.state(member)
            self.router._events.emit(
                "lease.grant", member=member, epoch=epoch
            )
        return True

    # -- autoscaling (service/autoscale.py drives these) -----------------------

    def scale_out(self) -> Optional[int]:
        """Grow the fleet by one replica at the next free index. The new
        member enters through `FleetRouter.rejoin`'s brand-new-index door:
        registered, leased, probed — but quarantined behind the same
        probation the rejoin path uses, so a flapping new member never
        receives work it would immediately orphan. Journals
        `fleet.scale_out`; counts `scale_outs`. Returns the new index, or
        None when the ``fleet.autoscale`` chaos point aborted the grow —
        which fires FIRST, before the grant and the spawn, so an injected
        fault changes literally nothing (not even a burned epoch).

        Shares `_rejoin_lock` with rejoin_replica: membership growth and
        member recovery are serialized against each other."""
        with self._rejoin_lock:
            try:
                maybe_fault("fleet.autoscale", action="scale_out")
            except FaultError:
                return None
            idx = len(self.replicas)
            member = lease_member(idx)
            proc = None
            if self.remote:
                from .remote import RemoteReplica, spawn_replica_proc

                self.lease_store.grant(member)
                proc, url = spawn_replica_proc(
                    idx, self.store_root, self._service_kw,
                    timeout_s=self._spawn_timeout_s,
                    scratch=self.scratch_dir,
                )
                new = RemoteReplica(
                    idx, url, proc=proc, tracer=self._tracer_raw,
                    store_root=self.store_root,
                )
            else:
                new = self._make_inproc_replica(idx)
            if not self.router.rejoin(new):
                # Unreachable for a brand-new index today; keep the same
                # no-leak teardown discipline as rejoin_replica anyway.
                if proc is not None:
                    self._kill_one(proc)
                else:
                    new.close()
                return None
            self.replicas.append(new)
            if proc is not None:
                self._procs.append(proc)
            if self.background:
                new.start()
            if self.lease_store is not None:
                epoch, _state = self.lease_store.state(member)
                self.router._events.emit(
                    "lease.grant", member=member, epoch=epoch
                )
            return idx

    def scale_in(self, idx: Optional[int] = None) -> Optional[int]:
        """Retire one replica — by default the least-loaded healthy
        member (ties retire the newest index). Loss-free by construction:
        the replica's RUNNING journaled jobs get one final checkpoint
        generation (in-proc; remote drivers checkpoint every spin
        anyway), then `FleetRouter.retire` revokes the lease, drains the
        backlog onto survivors (resumed where a generation exists), and
        only then is the local driver stopped. Journals `fleet.scale_in`;
        counts `scale_ins`. Returns the retired index, or None when
        there is no eligible member (never drains below one healthy
        replica) or the ``fleet.autoscale`` chaos point aborted the
        retirement — fired FIRST, so an injected fault leaves the fleet
        exactly as it was."""
        with self._rejoin_lock:
            try:
                maybe_fault("fleet.autoscale", action="scale_in")
            except FaultError:
                return None
            if idx is None:
                idx = self._scale_in_candidate()
            if idx is None or not (0 <= idx < len(self.replicas)):
                return None
            r = self.replicas[idx]
            if not self.remote and r.alive:
                # Final flush BEFORE the lease revoke inside retire():
                # after the revoke this driver's own writes would refuse
                # themselves, and the drain would restart instead of
                # resume.
                try:
                    r._checkpoint_jobs()
                except Exception:  # noqa: BLE001 — flush is best-effort
                    pass
            if not self.router.retire(idx):
                return None
            if self.remote:
                r.stop()  # completion mirror: the handles were requeued
                if getattr(r, "proc", None) is not None:
                    self._kill_one(r.proc)
            else:
                r.retire_driver()
            # The slot stays occupied (self.replicas is index-addressed;
            # the router keeps reporting the member as a dead row, and
            # close() reaps it from the list) — a later scale_out grows
            # at the NEXT index, and rejoin_replica can even resurrect
            # this one.
            return idx

    def _scale_in_candidate(self) -> Optional[int]:
        """Least-loaded healthy member by unfinished fleet-job count;
        probation/draining members are ineligible (mid-transition), and
        the last healthy member is never a candidate."""
        router = self.router
        with router._lock:
            live = [
                i for i in router.replicas
                if i not in router._dead
                and i not in router._draining
                and i not in router._probation
                and router.replicas[i].alive
            ]
            if len(live) <= 1:
                return None
            load: dict[int, int] = {}
            for fj in router._jobs.values():
                if (
                    fj.status not in FleetJobStatus.FINISHED
                    and fj.replica is not None
                ):
                    load[fj.replica] = load.get(fj.replica, 0) + 1
        return min(live, key=lambda i: (load.get(i, 0), -i))

    # -- client surface --------------------------------------------------------

    def submit(self, model, **opts):
        return self.router.submit(model, **opts)

    def stats(self) -> dict:
        return self.router.stats()

    def store_stats(self) -> Optional[dict]:
        rows = [
            r.service.store_stats()
            for r in self.replicas
            if r.alive and getattr(r, "service", None) is not None
        ]
        rows = [s for s in rows if s]
        return rows[0] if len(rows) == 1 else (rows or None)

    # -- foreground driving ----------------------------------------------------

    def pump(self, rounds: int = 1) -> int:
        """Foreground mode: drive every live replica and one router tick
        per round; returns how many replica pumps dispatched work."""
        ran = 0
        for _ in range(rounds):
            for r in self.replicas:
                if r.alive:
                    ran += 1 if r.spin() else 0
            self.router.tick()
        return ran

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every fleet job has finished (requeues included)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.router.all_done():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("fleet drain timed out")
            if self.background:
                time.sleep(0.005)  # router/replica threads make progress
            else:
                self.pump(4)

    @staticmethod
    def _kill_one(p) -> None:
        """SIGTERM first (the child drains + flushes its journal), then
        the hard kill — teardown must never hang on a wedged child."""
        try:
            if p.poll() is None:
                p.terminate()
                p.wait(timeout=10.0)
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        try:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=5.0)
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass

    def _kill_procs(self) -> None:
        """Stop every replica subprocess (rejoined incarnations included)."""
        for p in self._procs:
            self._kill_one(p)

    def _supervise(self) -> None:
        while not self._stop.is_set():
            self.router.tick()
            self._stop.wait(timeout=0.01)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._router_thread is not None:
            self._router_thread.join(timeout=5.0)
            self._router_thread = None
        for r in list(self.replicas) + self._retired:
            r.close()
        self.router.close()
        self._kill_procs()
        for j in self._journals:
            j.close()
        if self.lease_store is not None:
            self.lease_store.close()
        if self._scratch_tmp is not None:
            self._scratch_tmp.cleanup()
            self._scratch_tmp = None
        if self._tmpdir is not None:
            self._tmpdir.cleanup()
            self._tmpdir = None
